// accl-tpu native runtime: the SESSION translation unit.
//
// One instance per rank: an eager rx-buffer ring with (src, tag, seqn)
// seek matching (reference rxbuf_offload/*), rendezvous address/
// completion matching with pending queues (reference
// ccl_offload_control.c:142-408), the reliability sublayer's
// retransmit/ack policy, and a single sequencer thread running the
// call + retry queues round-robin with current_step resumption
// (reference run(), ccl_offload_control.c:2308-2483).
//
// The wire itself lives BELOW the POE seam (src/transport.h): this TU
// builds frames and hands them to a Poe (TCP mesh / UDP datagrams /
// in-process registry) as scatter-gather views, and receives inbound
// frames via PoeSink::on_frame. It never touches a socket.
//
// Collective algorithms mirror the firmware's selections exactly —
// eager/rendezvous split, ring vs flat vs binary tree by tuning register —
// the same rules accl_tpu/sequencer/plan.py encodes for the XLA path.

#include "../include/acclrt.h"

#include <atomic>
#include <cassert>
#include <chrono>
#include <condition_variable>
#include <cstddef>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <unordered_map>
#include <vector>

#include "reliability.h"
#include "transport.h"
#include "wire.h"

using namespace acclw;

namespace {

// ---------------------------------------------------------------------------
// Error codes (mirror accl_tpu.constants.ErrorCode / constants.hpp:341-376)
// ---------------------------------------------------------------------------
enum Err : uint32_t {
  NO_ERROR = 0,
  DMA_DECODE_ERROR = 1u << 2,
  RECEIVE_TIMEOUT_ERROR = 1u << 11,
  COLLECTIVE_NOT_IMPLEMENTED = 1u << 14,
  DMA_SIZE_ERROR = 1u << 18,
  ARITH_ERROR = 1u << 19,
  PACK_SEQ_NUMBER_ERROR = 1u << 21,
  COMPRESSION_ERROR = 1u << 22,
  DMA_TAG_MISMATCH_ERROR = 1u << 26,
  NOT_READY = 0x80000000u,  // internal: requeue with current_step saved
};

// Exchange-memory register offsets (constants.hpp:139-154).
enum Addr : uint32_t {
  RETCODE = 0x1FFC,
  IDCODE = 0x1FF8,
  CFGRDY = 0x1FF4,
  PERFCNT = 0x1FF0,
  // repurposed spare: allreduce payloads <= this (and > max_eager) run
  // the reference's rendezvous reduce+bcast composition (.c:1878-1887);
  // 0 = streamed ring at every size (measured default, emu_bench.csv)
  ALLREDUCE_COMPOSITION_MAX_COUNT = 0x1FD8,
  REDUCE_FLAT_TREE_MAX_COUNT = 0x1FD4,
  REDUCE_FLAT_TREE_MAX_RANKS = 0x1FD0,
  BCAST_FLAT_TREE_MAX_RANKS = 0x1FCC,
  GATHER_FLAT_TREE_MAX_COUNT = 0x1FC8,
  GATHER_FLAT_TREE_MAX_FANIN = 0x1FC4,
};

constexpr uint32_t TAG_ANY = 0xFFFFFFFFu;
constexpr uint32_t EXCHMEM_BYTES = 8192;


// Scenario ids (constants.hpp:190-216).
enum Scenario : uint32_t {
  SC_CONFIG = 0, SC_COPY = 1, SC_COMBINE = 2, SC_SEND = 3, SC_RECV = 4,
  SC_BCAST = 5, SC_SCATTER = 6, SC_GATHER = 7, SC_REDUCE = 8,
  SC_ALLGATHER = 9, SC_ALLREDUCE = 10, SC_REDUCE_SCATTER = 11,
  SC_BARRIER = 12, SC_ALLTOALL = 13, SC_NOP = 255,
};

// Wire format (MsgType/MsgHeader/MSG_MAGIC) lives in wire.h — shared
// with the transport side of the POE seam.

// ---------------------------------------------------------------------------
// Timed condition waits: gcc-10's libtsan has no pthread_cond_clockwait
// interceptor, and libstdc++ routes steady-clock wait_for/wait_until
// through clockwait — the wait's internal unlock/reacquire becomes
// invisible to TSan, so every lock pairing after a timed wait reports
// as a false race or double lock. In TSan builds route timed waits
// through the system clock, which takes the intercepted
// pthread_cond_timedwait path. These timeouts are heartbeat ticks and
// lost-wakeup backstops, not correctness deadlines, so wall-clock
// sensitivity is acceptable in the sanitizer lane.
// ---------------------------------------------------------------------------
template <class Rep, class Period>
static std::cv_status cv_wait_for(std::condition_variable &cv,
                                  std::unique_lock<std::mutex> &lk,
                                  std::chrono::duration<Rep, Period> d) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(lk, std::chrono::system_clock::now() + d);
#else
  return cv.wait_for(lk, d);
#endif
}

template <class Rep, class Period, class Pred>
static bool cv_wait_for(std::condition_variable &cv,
                        std::unique_lock<std::mutex> &lk,
                        std::chrono::duration<Rep, Period> d, Pred p) {
#if defined(__SANITIZE_THREAD__)
  return cv.wait_until(lk, std::chrono::system_clock::now() + d,
                       std::move(p));
#else
  return cv.wait_for(lk, d, std::move(p));
#endif
}

// ---------------------------------------------------------------------------
// dtype helpers: elementwise SUM/MAX incl. fp16/bf16 via uint16 conversion
// (reduce_ops plugin analog, here over host memory)
// ---------------------------------------------------------------------------

static inline float half_to_float(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t man = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;
    } else {  // subnormal
      int e = -1;
      do { man <<= 1; e++; } while (!(man & 0x400));
      bits = sign | ((127 - 15 - e) << 23) | ((man & 0x3FF) << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (man << 13);
  } else {
    bits = sign | ((exp + 127 - 15) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

static inline uint16_t float_to_half(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  uint32_t exp8 = (bits >> 23) & 0xFF;
  uint32_t man = bits & 0x7FFFFF;
  if (exp8 == 0xFF)  // inf / NaN propagate
    return (uint16_t)(sign | 0x7C00 | (man ? 0x200 : 0));
  int32_t exp = (int32_t)exp8 - 127 + 15;
  if (exp <= 0) {
    // subnormal fp16 (matches IEEE/ml_dtypes/XLA, not flush-to-zero):
    // shift the full 24-bit significand right with round-to-nearest-even
    if (exp < -10) return (uint16_t)sign;  // underflows even subnormals
    uint32_t sig = man | 0x800000;         // implicit leading 1
    uint32_t shift = (uint32_t)(14 - exp); // 14..24
    uint32_t kept = sig >> shift;
    uint32_t rem = sig & ((1u << shift) - 1);
    uint32_t half_pt = 1u << (shift - 1);
    if (rem > half_pt || (rem == half_pt && (kept & 1)))
      kept++;  // may carry into the normal range (exp field 1) — still valid
    return (uint16_t)(sign | kept);
  }
  if (exp >= 31) return (uint16_t)(sign | 0x7C00); // overflow to inf
  // round to nearest even: add 0xFFF + the lsb of the kept mantissa
  uint32_t rounded = man + 0xFFF + ((man >> 13) & 1);
  if (rounded & 0x800000) {
    rounded = 0;
    exp++;
    if (exp >= 31) return (uint16_t)(sign | 0x7C00);
  }
  return (uint16_t)(sign | (exp << 10) | (rounded >> 13));
}

static inline float bf16_to_float(uint16_t h) {
  uint32_t bits = ((uint32_t)h) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

static inline uint16_t float_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7FFF + lsb;  // round to nearest even
  return (uint16_t)(bits >> 16);
}

static uint32_t dtype_bytes(uint32_t dt) {
  switch (dt) {
    case ACCL_DT_INT8: return 1;
    case ACCL_DT_FLOAT16: case ACCL_DT_BFLOAT16: return 2;
    case ACCL_DT_FLOAT32: case ACCL_DT_INT32: return 4;
    case ACCL_DT_FLOAT64: case ACCL_DT_INT64: return 8;
    default: return 0;
  }
}

template <typename T, typename Op>
static void combine_typed(void *a, const void *b, size_t n, Op op) {
  T *pa = (T *)a;
  const T *pb = (const T *)b;
  for (size_t i = 0; i < n; i++) pa[i] = op(pa[i], pb[i]);
}

// a := op(a, b), elementwise over n elements. func: 0=SUM, 1=MAX.
static uint32_t combine_buffers(uint32_t dt, uint32_t func, void *a,
                                const void *b, size_t n) {
  auto do16 = [&](auto to_f, auto from_f) {
    uint16_t *pa = (uint16_t *)a;
    const uint16_t *pb = (const uint16_t *)b;
    for (size_t i = 0; i < n; i++) {
      float x = to_f(pa[i]), y = to_f(pb[i]);
      pa[i] = from_f(func == 0 ? x + y : (x > y ? x : y));
    }
  };
  switch (dt) {
    case ACCL_DT_FLOAT32:
      func == 0 ? combine_typed<float>(a, b, n, [](float x, float y) { return x + y; })
                : combine_typed<float>(a, b, n, [](float x, float y) { return x > y ? x : y; });
      return NO_ERROR;
    case ACCL_DT_FLOAT64:
      func == 0 ? combine_typed<double>(a, b, n, [](double x, double y) { return x + y; })
                : combine_typed<double>(a, b, n, [](double x, double y) { return x > y ? x : y; });
      return NO_ERROR;
    case ACCL_DT_INT32:
      func == 0 ? combine_typed<int32_t>(a, b, n, [](int32_t x, int32_t y) { return x + y; })
                : combine_typed<int32_t>(a, b, n, [](int32_t x, int32_t y) { return x > y ? x : y; });
      return NO_ERROR;
    case ACCL_DT_INT64:
      func == 0 ? combine_typed<int64_t>(a, b, n, [](int64_t x, int64_t y) { return x + y; })
                : combine_typed<int64_t>(a, b, n, [](int64_t x, int64_t y) { return x > y ? x : y; });
      return NO_ERROR;
    case ACCL_DT_FLOAT16: do16(half_to_float, float_to_half); return NO_ERROR;
    case ACCL_DT_BFLOAT16: do16(bf16_to_float, float_to_bf16); return NO_ERROR;
    default: return ARITH_ERROR;
  }
}

// CRC32C + frame_crc live in reliability.{h,cpp} (session-side; the
// transport never computes integrity).

// ---------------------------------------------------------------------------
// runtime
// ---------------------------------------------------------------------------

// Hop-shape constants SINGLE-SOURCED with the Python timing model
// (accl_tpu/constants.py LOGP_ALLREDUCE_HOP_BYTES /
// LOGP_ALLGATHER_HOP_BYTES / STREAM_SEG_BYTES); tests/test_timing.py
// pins the two definitions together so the model cannot silently drift
// from this executor.
static constexpr uint64_t LOGP_ALLREDUCE_HOP_BYTES = 32 * 1024;
static constexpr uint64_t LOGP_ALLGATHER_HOP_BYTES = 128 * 1024;
static constexpr uint64_t STREAM_SEG_BYTES = 1ull << 20;

struct RxSlot {
  enum { IDLE, VALID } status = IDLE;
  uint32_t src = 0, tag = 0, seqn = 0;
  uint32_t lane = 0;  // the (src, lane) seqn stream this segment rides
  uint64_t msg_bytes = 0;  // total length of the message this segment is of
  uint64_t msg_off = 0;    // this segment's byte offset inside that message
  // landing time: a strict recv meeting a MISMATCHED head defers while
  // the head is young (another consumer's traffic interleaved on the
  // link) and only fails fast once it has provably gone unclaimed
  std::chrono::steady_clock::time_point t_land{};
  std::vector<uint8_t> data;
};

struct RndzvAddr {
  uint32_t src;
  uint64_t vaddr;
  uint64_t bytes;
  uint32_t tag;
  uint32_t host;
  // one-sided writes land DIRECTLY at vaddr (no staging copy): in_use
  // pins the target across the rx thread's poll-bounded read; abort is
  // the revoker's bounded-wait handshake (same protocol as
  // accl_rt::EagerLanding). Only meaningful inside posted_addrs.
  bool in_use = false;
  bool abort = false;
};

struct RndzvDone {
  uint32_t src;
  uint64_t vaddr;
  uint64_t bytes;
  uint32_t tag;
};

// Resolved communicator view: group size, this rank's position in the
// group, and the group-rank -> global-rank map (empty = identity over the
// transport world). The firmware equivalent caches the communicator
// addressed by the descriptor's comm_addr per call
// (ccl_offload_control.c:2317-2372).
struct CommView {
  uint32_t world = 0;
  uint32_t rank = 0;
  std::vector<uint32_t> map;
  uint32_t g(uint32_t r) const { return map.empty() ? r : map[r]; }
};

// Per-call persistent collective state across NOT_READY requeues: every
// do_* below is a step-indexed state machine riding Call.current_step (the
// firmware requeues ANY NOT_READY collective with current_step,
// ccl_offload_control.c:2308-2483), and this carries the data a resumed
// pass needs that does not live in caller memory.
struct CollState {
  uint64_t off = 0;  // current op's partial progress: eager bytes landed,
                     // or the rendezvous posted-address marker
  // SC_RECV posted-order FIFO ticket (see the recv op): assigned on the
  // call's first eager pass, dropped with the registry entry on terminal
  uint64_t ticket = 0;
  bool ticketed = false;
  // direct-placement landing registered for the CURRENT recv op (see
  // accl_rt::EagerLanding); cleared when the op completes
  bool landing = false;
  // Config/tuning SNAPSHOT taken on the call's first pass: the replayed
  // op sequence must be deterministic, and a config call (or tuning
  // register write) executing between requeue passes of a parked
  // collective must not flip its protocol/algorithm branches mid-flight.
  bool cfg = false;
  uint32_t max_eager = 0;
  uint64_t max_rndzv = 0;
  uint32_t tun_bcast_ranks = 0, tun_gather_fanin = 0, tun_gather_count = 0,
           tun_reduce_ranks = 0, tun_reduce_count = 0;
  uint64_t tun_allred_comp = 0;
  int wire_bf16 = -1;  // compressed wire dtype, snapshotted like the rest
  // algorithm scratch that must survive requeues (reduce accumulators,
  // ring relay buffers, rendezvous landing slots, the reduce_scatter
  // composition's full-width intermediate)
  std::vector<uint8_t> acc, tmp, full;
  // addresses THIS call posted and has not yet seen complete: revoked on
  // timeout so a late write cannot land in memory the caller reuses
  std::deque<RndzvAddr> posted;
  void unpost(uint64_t vaddr) {
    for (auto it = posted.begin(); it != posted.end(); ++it)
      if (it->vaddr == vaddr) { posted.erase(it); return; }
  }
};

struct Call {
  int64_t handle;
  uint32_t desc[15];
  uint32_t dtype;
  void *op0, *op1, *res;
  bool started = false;  // has executed at least one pass (holds its
                         // communicator's in-flight serialization slot)
  // counted once against the ACCL_RT_FAULT_KILL_AFTER budget (a
  // NOT_READY requeue must not burn the countdown twice)
  bool started_counted = false;
  uint32_t current_step = 0;  // resumption point across NOT_READY requeues
  // resolved communicator persists across requeues like current_step
  bool comm_resolved = false;
  CommView comm;
  bool deadline_set = false;
  std::chrono::steady_clock::time_point deadline;
  std::chrono::steady_clock::time_point t_start;
  // compressed-domain scratch: persists across retry requeues so partial
  // progress (already-landed segments) survives re-execution
  std::shared_ptr<std::vector<uint16_t>> c16_op0, c16_op1, c16_res;
  // step-machine scratch (shared with the compressed-domain inner Call)
  std::shared_ptr<CollState> cstate;
  // trace-ring bookkeeping (ACCL_RT_TRACE=1): sequencer-counter snapshot
  // at enqueue (the span's per-call delta base) and the deferred-head-
  // mismatch fault code behind an eventual RECEIVE_TIMEOUT
  uint64_t ctr0[4] = {0, 0, 0, 0};
  uint32_t trace_detail = 0;
  // last_defer.count at this call's first pass: the timeout detail may
  // only report mismatches recorded DURING the call — a stale note from
  // an earlier (resolved) deferral must not masquerade as this
  // timeout's root cause
  uint64_t defer0 = 0;
};

struct Completion {
  std::atomic<int> done{0};
  uint32_t retcode = 0;
  uint64_t duration_ns = 0;
};

}  // namespace

struct accl_rt : public acclw::PoeSink {
  uint32_t world, rank;  // ACCL_INIT_CONST
  uint32_t rx_buf_bytes;  // ACCL_INIT_CONST
  uint32_t max_eager;  // ACCL_ROLE_ONLY(seq); SC_CONFIG-mutable
  uint64_t max_rndzv;  // ACCL_ROLE_ONLY(seq); SC_CONFIG-mutable
  std::vector<uint8_t> exchmem = std::vector<uint8_t>(EXCHMEM_BYTES, 0);  // ACCL_GUARDED_BY(exch_mu)
  std::mutex exch_mu;

  // The Protocol Offload Engine behind the seam (src/transport.h) — TCP
  // full mesh (session-based, the EasyNet-class POE), one shared
  // datagram socket (sessionless, the VNX-UDP POE analog: every segment
  // a standalone packet carrying the full 64 B header, reassembled
  // purely by (src, tag, seqn) — the udp_depacketizer role), or the
  // intra-process registry (direct-call delivery, the intra-node
  // fast-path role NCCL fills with SHM/P2P transports). The session
  // builds frames and hands the Poe scatter-gather views; inbound
  // frames arrive via on_frame (the PoeSink side of this struct).
  std::unique_ptr<acclw::Poe> poe;  // ACCL_INIT_CONST
  bool udp_mode = false;  // ACCL_INIT_CONST
  // Per-peer LANES (TCP only, ACCL_RT_LANES, clamped [1, 2]): each
  // (peer, lane) pair is an independent ordered link carrying its own
  // seqn stream, so a jumbo eager message on the bulk lane (lane 1,
  // messages >= lane_bulk_bytes) cannot head-of-line-block a small
  // message on the default lane. All per-peer stream state below is
  // indexed by sid = rank * n_lanes + lane. Default 1 lane — the
  // single-stream wire, bit-identical to the pre-lane protocol.
  uint32_t n_lanes = 1;  // ACCL_INIT_CONST
  uint64_t lane_bulk_bytes = 64ull << 10;  // ACCL_INIT_CONST; ACCL_RT_LANE_BULK_BYTES
  bool legacy_wire = false;  // ACCL_INIT_CONST; ACCL_RT_WIRE_LEGACY: per-frame-syscall
                             // cost model, batching off (bench A/B)
  bool tx_batch_on = false;  // ACCL_INIT_CONST; computed at create: vectored batching
                             // armed (off under chaos/WAN/legacy/local
                             // — those paths need per-frame emission)
  uint32_t sid(uint32_t r, uint32_t lane) const { return r * n_lanes + lane; }
  uint32_t lane_of(uint64_t msg_bytes) const {
    return (n_lanes > 1 && msg_bytes >= lane_bulk_bytes) ? 1u : 0u;
  }
  std::vector<bool> hello_seen;      // ACCL_GUARDED_BY(hello_mu); bring-up handshake state
  std::mutex hello_mu;
  std::condition_variable hello_cv;
  std::atomic<bool> stop{false};

  // eager rx ring + notifications (rxbuf_offload analog). idle_q is the
  // IDLE free-list (indices into rx_slots) so landing a segment is O(1)
  // even when the datagram transport grows the ring into the thousands.
  std::vector<RxSlot> rx_slots;  // ACCL_GUARDED_BY(rx_mu)
  std::vector<size_t> idle_q;  // ACCL_GUARDED_BY(rx_mu)
  size_t base_rx_slots = 0;  // ACCL_INIT_CONST; configured ring size; growth beyond it is
                             // burst absorption and compacts when drained
  // (sid, seqn) -> slot index: seeks are O(1) even when a datagram burst
  // grows the ring to 2^20 slots (a linear scan made draining a large
  // burst quadratic). src_valid_count keeps stray-seqn detection O(1).
  // All stream-indexed maps below key on sid = src * n_lanes + lane —
  // each lane is its own ordered seqn stream.
  std::unordered_map<uint64_t, size_t> rx_index;  // ACCL_GUARDED_BY(rx_mu)
  std::vector<uint32_t> src_valid_count;  // ACCL_GUARDED_BY(rx_mu)
  // sid -> the call (CollState address) that has consumed part of a
  // multi-segment eager message from that src and owns the remainder of
  // its stream: segments of one message share tag and consecutive seqns,
  // so a DIFFERENT call matching the next head by tag would interleave
  // payload mid-message (two concurrent TAG_ANY recvs, or a recv racing
  // a collective on the same src link). Guarded by rx_mu; released on
  // message completion or call termination (release_rx_ownership).
  std::unordered_map<uint32_t, const void *> rx_stream_owner;  // ACCL_GUARDED_BY(rx_mu)
  static uint64_t rx_key(uint32_t sid, uint32_t seqn) {
    return ((uint64_t)sid << 32) | seqn;
  }

  // Outstanding SC_RECV registry for posted-order FIFO pairing (see the
  // recv op). Guarded by rx_mu, like the stream-owner map.
  struct OutstandingRecv {
    uint32_t src, tag;
    uint64_t bytes, ticket;
    const void *tok;
  };
  std::vector<OutstandingRecv> outstanding_recvs;  // ACCL_GUARDED_BY(rx_mu)
  uint64_t recv_ticket_next = 0;  // ACCL_GUARDED_BY(rx_mu)

  // Last strict-recv head mismatch that DEFERRED instead of erroring
  // (the head_is_claimable softening in seek_locked): a deferred
  // protocol fault that never resolves surfaces as a plain
  // RECEIVE_TIMEOUT, so the mismatch is recorded here and echoed in the
  // eventual timeout detail. Guarded by rx_mu like the rx state it
  // describes.
  struct DeferNote {
    uint64_t count = 0;  // defers recorded since bring-up
    uint32_t src = 0;
    uint32_t head_tag = 0, want_tag = 0, head_seqn = 0;
    uint64_t head_msg = 0, head_off = 0, want_msg = 0;
    // the fault code the mismatch WOULD have raised had the head been
    // provably stray (DMA_TAG_MISMATCH_ERROR / DMA_SIZE_ERROR): the
    // NOT_READY softening must not hide which protocol check tripped
    uint32_t code = 0;
  } last_defer;  // ACCL_GUARDED_BY(rx_mu)
  // ACCL_REQUIRES(rx_mu)
  void note_defer_locked(const RxSlot &s, uint32_t want_tag,
                         uint64_t want_msg, uint32_t code) {
    last_defer.count++;
    last_defer.src = s.src;
    last_defer.head_tag = s.tag;
    last_defer.want_tag = want_tag;
    last_defer.head_seqn = s.seqn;
    last_defer.head_msg = s.msg_bytes;
    last_defer.head_off = s.msg_off;
    last_defer.want_msg = want_msg;
    last_defer.code = code;
  }

  // Direct-placement eager landing (rxbuf bypass): a parked strict recv
  // registers its destination so the rx thread reads subsequent
  // segments of ITS message straight into the final buffer — no slot
  // allocation, no staging copy. The eager-path analog of the
  // reference's zero-copy rendezvous write (rendezvous lands at the
  // posted vaddr), sized for the streamed whole-chunk collectives where
  // the bytes are. TCP only: the ordered link guarantees the next
  // segments are the message's continuation; datagram reordering keeps
  // the slot path. Guarded by rx_mu; `in_use` pins the buffer while the
  // rx thread is mid-read (revocation waits on it).
  struct EagerLanding {
    uint8_t *base = nullptr;
    uint64_t want = 0, landed = 0;
    uint32_t tag = 0;
    bool in_use = false;  // rx thread mid-read into base
    bool abort = false;   // revoker asked the rx thread to let go
    const void *tok = nullptr;
  };
  std::unordered_map<uint32_t, EagerLanding> eager_landings;  // ACCL_GUARDED_BY(rx_mu); by sid

  // Remove a call's landings (rx_mu held via lk). An in-flight direct
  // read is asked to let go via `abort`; the rx thread's read loop is
  // poll-bounded (it re-checks under rx_mu at least every 100 ms even
  // against a frozen peer), acknowledges by clearing in_use and
  // diverting the rest of the segment to scratch, so this wait is
  // BOUNDED — the sequencer cannot wedge behind a dead link the way an
  // unbounded recv_all wait would. A partially-landed message arms the
  // orphan drain for its tail. The cv wait releases the lock, so the
  // scan restarts after every wakeup (iterators don't survive the gap).
  // ACCL_REQUIRES(rx_mu)
  void drop_landings_locked(std::unique_lock<std::mutex> &lk,
                            const void *tok) {
    for (;;) {
      auto it = eager_landings.begin();
      for (; it != eager_landings.end(); ++it)
        if (it->second.tok == tok) break;
      if (it == eager_landings.end()) return;
      if (it->second.in_use) {
        it->second.abort = true;
        cv_wait_for(rx_cv, lk, std::chrono::milliseconds(250));
        continue;
      }
      if (it->second.landed > 0 && it->second.landed < it->second.want)
        rx_drain_srcs.insert(it->first);
      eager_landings.erase(it);
    }
  }
  // sids whose seqn head may hold orphaned continuation segments of a
  // message whose recv died mid-consumption: seek discards segments with
  // msg_off != 0 until the next message head surfaces. Guarded by rx_mu.
  std::set<uint32_t> rx_drain_srcs;  // ACCL_GUARDED_BY(rx_mu)

  // Drop every rx-side claim a terminating call holds: its stream
  // ownership AND its outstanding-recv ticket (a dead elder must not
  // defer younger recvs forever). An ownership entry still present here
  // means the call died mid-message — arm the orphan drain for that src.
  void release_rx_ownership(const void *tok) {
    std::unique_lock<std::mutex> lk(rx_mu);
    drop_landings_locked(lk, tok);
    for (auto it = rx_stream_owner.begin(); it != rx_stream_owner.end();) {
      if (it->second == tok) {
        rx_drain_srcs.insert(it->first);
        it = rx_stream_owner.erase(it);
      } else {
        ++it;
      }
    }
    for (auto it = outstanding_recvs.begin(); it != outstanding_recvs.end();)
      it = (it->tok == tok) ? outstanding_recvs.erase(it) : std::next(it);
    rx_cv.notify_all();
  }
  std::mutex rx_mu;
  std::condition_variable rx_cv;

  // rendezvous pending queues (CMD/STS_RNDZV(_PENDING) analog)
  std::deque<RndzvAddr> addr_q;  // ACCL_GUARDED_BY(rndzv_mu)
  std::deque<RndzvDone> done_q;  // ACCL_GUARDED_BY(rndzv_mu)
  // addresses this rank has posted via rendezvous_send_addr, keyed by
  // vaddr with the peer allowed to write them: the ONLY targets a
  // MSG_RNDZV_WRITE may land on (anything else is an arbitrary-write
  // attempt and is dropped)
  std::deque<RndzvAddr> posted_addrs;  // ACCL_GUARDED_BY(rndzv_mu); src = the peer we posted to
  std::mutex rndzv_mu;
  std::condition_variable rndzv_cv;

  // per-(peer, lane) sequence numbers (ccl_offload_control.h:297-310),
  // indexed by sid — each lane is an independent ordered stream
  std::vector<uint32_t> inbound_seq;   // ACCL_GUARDED_BY(rx_mu)
  std::vector<uint32_t> outbound_seq;  // ACCL_ROLE_ONLY(seq)

  // call + retry queues and sequencer thread (run() analog). Calls on the
  // SAME communicator execute FIFO, one in flight at a time: the eager
  // wire carries no call identity (per-src seqn streams only), so letting
  // a second same-comm collective start while the first is parked would
  // let it consume the first's segments. Different comm_addrs interleave
  // freely — that is the disjoint-communicator concurrency the retry
  // queue exists for; OVERLAPPING groups at different table addresses
  // need distinct tags, the documented eager-wire contract.
  std::map<uint32_t, uint32_t> inflight_comms;  // ACCL_GUARDED_BY(call_mu); comm_addr -> started calls
  std::deque<Call> call_q, retry_q;  // ACCL_GUARDED_BY(call_mu)
  std::mutex call_mu;
  std::condition_variable call_cv;
  std::thread seq_thread;
  std::map<int64_t, std::shared_ptr<Completion>> completions;  // ACCL_GUARDED_BY(comp_mu)
  std::mutex comp_mu;
  std::condition_variable comp_cv;
  int64_t next_handle = 1;  // ACCL_GUARDED_BY(comp_mu)

  uint64_t timeout_ms = 5000;  // ACCL_ROLE_ONLY(seq); SC_CONFIG-mutable

  // ACCL_RT_STATS=1 diagnostics: sequencer behavior counters
  std::atomic<uint64_t> stat_passes{0}, stat_parks{0}, stat_park_ns{0},
      stat_seek_miss{0}, stat_seek_hit{0};

  // Device-resident trace ring (ACCL_RT_TRACE=1): one accl_rt_span_t
  // per completed call, fixed capacity (ACCL_RT_TRACE_CAP, default
  // 4096). Overflow drops the OLDEST span and counts it — tracing can
  // degrade under load but never blocks or crashes the data plane. The
  // perf-counter-next-to-the-data-plane posture of the CCLO's duration
  // registers, with the host draining after the fact
  // (accl_rt_trace_read -> emu_device.EmuRank.trace_read).
  bool trace_on = false;  // ACCL_INIT_CONST
  size_t trace_cap = 4096;  // ACCL_INIT_CONST
  std::deque<accl_rt_span_t> trace_q;  // ACCL_GUARDED_BY(trace_mu)
  uint64_t trace_dropped = 0;  // ACCL_GUARDED_BY(trace_mu)
  std::mutex trace_mu;
  std::chrono::steady_clock::time_point t_create =  // ACCL_INIT_CONST
      std::chrono::steady_clock::now();

  void record_span(const Call &c, uint32_t rc) {
    accl_rt_span_t s{};
    s.opcode = c.desc[0];
    s.retcode = rc;
    s.detail = c.trace_detail;
    s.count = c.desc[1];
    s.bytes = (uint64_t)c.desc[1] * dtype_bytes(c.dtype);
    auto ns_since = [&](std::chrono::steady_clock::time_point t) {
      return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
                 t - t_create)
          .count();
    };
    s.start_ns = ns_since(c.t_start);
    s.end_ns = ns_since(std::chrono::steady_clock::now());
    s.d_passes = stat_passes.load() - c.ctr0[0];
    s.d_parks = stat_parks.load() - c.ctr0[1];
    s.d_seek_hit = stat_seek_hit.load() - c.ctr0[2];
    s.d_seek_miss = stat_seek_miss.load() - c.ctr0[3];
    std::lock_guard<std::mutex> g(trace_mu);
    if (trace_q.size() >= trace_cap) {
      trace_q.pop_front();  // oldest spans yield to fresh ones
      trace_dropped++;
    }
    trace_q.push_back(s);
  }

  // ACCL_RT_SHAPE=ring|logp overrides the hop-shape auto rule for
  // allreduce/allgather (0 auto, 1 ring, 2 recursive halving/doubling):
  // the benchmark harness sweeps both to calibrate the crossover
  // (tools/rt_stats_sweep.py --shape).
  int shape_override = 0;  // ACCL_INIT_CONST

  // BFM-style wire-fault injection (the reference test strategy drives
  // its DUT through a bus-functional model that can corrupt/delay
  // streams — SURVEY.md §4; tests/test_fault_injection.py):
  //   ACCL_RT_FAULT_DELAY_TAIL_MS=N  the FIRST multi-segment eager
  //     message sent delays its final segment by N ms (a slow tail: the
  //     consumer's recv dies mid-message and must orphan-drain);
  //   ACCL_RT_FAULT_DROP_TAIL=1      the FIRST multi-segment eager
  //     message loses its final segment outright (datagram-transport
  //     loss semantics: the seqn gap must surface as a clean timeout).
  // One-shot by design: the fault arms once per runtime.
  int fault_delay_tail_ms = 0;  // ACCL_INIT_CONST
  bool fault_drop_tail = false;  // ACCL_INIT_CONST
  //   ACCL_RT_FAULT_KILL_RANK=R       rank R wedges PERMANENTLY (not the
  //     one-shot tail levers above): after ACCL_RT_FAULT_KILL_AFTER=N
  //     completed data-plane calls (default 0 — the very next call dies)
  //     every in-flight and future call on the rank completes with a
  //     sticky RECEIVE_TIMEOUT retcode — recorded as a FINAL trace-ring
  //     span, so the host flight recorder fires on the death — and the
  //     wire goes dark in both directions: outbound frames are dropped
  //     before the transport, inbound frames are read off the socket
  //     (framing preserved for the peer's tx path) and discarded. Peers
  //     observe exactly what a dead host produces: silence, surfacing as
  //     their own recv deadlines. accl_rt_kill() is the programmatic
  //     form (the fault-gate soak kills a rank mid-stream).
  std::atomic<bool> killed{false};
  int kill_after_calls = -1;  // ACCL_ROLE_ONLY(seq); sequencer-thread only; -1 = unarmed

  void wedge() {
    killed.store(true, std::memory_order_release);
    // wake everything that could be parked so in-flight calls reach
    // the kill check (and die with their sticky span) promptly
    rx_event();
    call_cv.notify_all();
    rndzv_cv.notify_all();
  }
  // ACCL_RT_WAN_ALPHA_US / ACCL_RT_WAN_GBPS: WAN shaper for the socket
  // transports — every outbound frame pays alpha + bytes/beta on its
  // per-destination link (inside tx_mu, so frames to one peer
  // serialize like a real wire) before entering the kernel, turning
  // loopback sockets into an emulated slow cross-slice (DCN) tier.
  // Read at create, so one process can hold differently-shaped worlds:
  // the bench's emulated 2-tier world is unshaped local-POE pods
  // (fast ICI tier) beside shaped TCP groups (slow DCN tier). The
  // local POE is never shaped — it IS the fast tier.
  uint32_t wan_alpha_us = 0;  // ACCL_INIT_CONST
  double wan_bytes_per_us = 0.0;  // ACCL_INIT_CONST

  void wan_charge(size_t payload_len) {
    if (!wan_alpha_us && wan_bytes_per_us <= 0) return;
    double us = (double)wan_alpha_us;
    if (wan_bytes_per_us > 0)
      us += (double)(sizeof(MsgHeader) + payload_len) / wan_bytes_per_us;
    if (us >= 1.0)
      std::this_thread::sleep_for(
          std::chrono::microseconds((long long)us));
  }
  std::atomic<bool> fault_armed{false};
  std::vector<std::thread> fault_threads;
  std::mutex fault_mu;

  // ----- reliability sublayer (ACCL_RT_RELY, default on) ------------------
  // CRC32C frame integrity + per-(peer, seqn) selective retransmit: the
  // delivery guarantees the reference offload engine owns below the
  // host (README.md:6 — the host never sees a lost segment), rebuilt at
  // this wire. Sender side: every MSG_EGR_DATA frame is serialized and
  // kept in a per-destination bounded retransmit buffer until the
  // peer's cumulative MSG_ACK releases it; a MSG_NACK resends the raw
  // frame bytes. Receiver side: a seek miss records the wanted (src,
  // seqn) and the health thread NACKs it with bounded exponential
  // backoff (short first delay when stray seqns prove a gap, a longer
  // one for a possibly-not-yet-sent head); repaired frames re-land
  // idempotently on the existing dedup path (late/duplicate seqns
  // drop). The budget is bounded on BOTH axes — nack attempts and
  // retransmit-buffer bytes — so an unrecoverable frame degrades to
  // the existing RECEIVE_TIMEOUT escalation, never an unbounded stall.
  // World-uniform: every rank of a world must run the same rely mode
  // (a rely-off sender's crc=0 frames fail a rely-on receiver's check).
  bool rely_on = true;  // ACCL_INIT_CONST
  // the EFFECTIVE wire flag: rely_on, except on the in-process local
  // POE with no fault model armed — that "wire" is a synchronous
  // function call that cannot lose or corrupt frames, so CRC + retx
  // retention there is pure overhead protecting against nothing (both
  // sides of a local world share the process env, so the mode is
  // world-uniform by construction)
  bool rely_wire = true;  // ACCL_INIT_CONST
  bool debug_on = false;  // ACCL_INIT_CONST; ACCL_RT_DEBUG, read once at create: wire
                          // drop/tx prints are gated on this AND counted
                          // in stats, so a chaos soak never spams stderr
  uint64_t retx_budget_bytes = 16ull << 20;  // ACCL_INIT_CONST; per dst, oldest evicted
  uint32_t nack_max = 24;                    // ACCL_INIT_CONST; per-seqn attempt budget
  // RetxFrame/RetxBuf/HeldFrame/WantState are the shared reliability
  // types (reliability.h); retention is BY REFERENCE — the FramePtr in
  // the retx buffer is the same serialized frame the wire shipped.
  std::vector<RetxBuf> retx;  // ACCL_GUARDED_BY(rely_mu); per (dst, lane) sid; rely_mu
  // retransmits requested by peers, drained by the HEALTH thread: the
  // rx thread must never perform a blocking data-frame send itself —
  // two peers simultaneously retransmitting jumbo frames to each other
  // from their rx loops would stop draining their sockets while
  // blocked in send_all and mutually wedge both links (a liveness
  // hazard the pre-rely rx thread never had). rely_mu.
  std::deque<FramePtr> retx_pending;  // ACCL_GUARDED_BY(rely_mu); dst + lane ride the header
  std::unordered_map<uint32_t, HeldFrame> reorder_held;  // ACCL_GUARDED_BY(rely_mu); by sid; rely_mu
  std::mutex rely_mu;
  std::thread rely_thread;
  // receiver-side per-src want/ack state (rx_mu, like the rx state it
  // describes). want = the head seqn a consumer is provably waiting on
  // (recorded at seek miss); acked_upto = the last cumulative ack sent.
  std::vector<WantState> want;  // ACCL_GUARDED_BY(rx_mu); per (src, lane) sid
  std::vector<uint32_t> acked_upto;  // ACCL_GUARDED_BY(rx_mu)
  std::vector<std::chrono::steady_clock::time_point> last_ack_t;  // ACCL_GUARDED_BY(rx_mu)

  // Seeded bus-functional fault model (generalizes the one-shot
  // DROP_TAIL/DELAY_TAIL levers; the reference drives its DUT through a
  // BFM that can corrupt/delay streams, SURVEY.md §4):
  //   ACCL_RT_FAULT_LOSS_PCT     frame vanishes before the transport
  //   ACCL_RT_FAULT_CORRUPT_PCT  one payload bit flips (zero-payload
  //                              frames flip a crc-field bit) AFTER the
  //                              CRC is computed — framing stays intact,
  //                              the receiver's check must catch it
  //   ACCL_RT_FAULT_DUP_PCT      frame delivered twice
  //   ACCL_RT_FAULT_REORDER_PCT  frame held and swapped with the next
  //                              frame to the same dst (health thread
  //                              releases a tail hold after ~2 ms)
  //   ACCL_RT_FAULT_SEED         deterministic per-rank PRNG seed
  // Applied to freshly-sent MSG_EGR_DATA frames only (control frames
  // and retransmits ride clean, so repair always converges); drawn from
  // a per-runtime splitmix64 stream, so a given (seed, rank, frame
  // order) chaos run is reproducible.
  double fault_loss_pct = 0, fault_corrupt_pct = 0;  // ACCL_INIT_CONST
  double fault_dup_pct = 0, fault_reorder_pct = 0;  // ACCL_INIT_CONST
  bool fault_pct_armed = false;  // ACCL_INIT_CONST
  uint64_t rng_state = 0;  // ACCL_GUARDED_BY(rng_mu)
  std::mutex rng_mu;
  double rng_u01() {  // splitmix64 -> [0, 1)
    std::lock_guard<std::mutex> g(rng_mu);
    rng_state += 0x9E3779B97F4A7C15ull;
    uint64_t z = rng_state;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    z ^= z >> 31;
    return (double)(z >> 11) / (double)(1ull << 53);
  }

  // wire-health counters (the accl_rt_get_stats2 surface)
  std::atomic<uint64_t> stat_tx_frames{0}, stat_rx_frames{0},
      stat_crc_drops{0}, stat_dup_drops{0}, stat_retx_sent{0},
      stat_retx_miss{0}, stat_nack_sent{0}, stat_nack_rx{0},
      stat_ack_sent{0}, stat_ack_rx{0}, stat_rndzv_drops{0},
      stat_inj_loss{0}, stat_inj_corrupt{0}, stat_inj_dup{0},
      stat_inj_reorder{0}, stat_rely_ns{0};
  // A delayed tail still in flight to fault_tail_dst: new egr traffic to
  // that dst before it lands would break wire order (the lever's one
  // precondition) — detected race-free at the SENDER, which owns
  // outbound_seq, instead of peeking the counter from the delay thread.
  std::atomic<bool> fault_tail_pending{false};
  std::atomic<uint32_t> fault_tail_dst{0};

  // intra-process POE (registry + pinning live in the LocalPoe)
  bool local_mode = false;  // ACCL_INIT_CONST

  // Generation counter of rx-side progress events (eager landings,
  // rendezvous addresses/completions): the sequencer snapshots it before
  // an execute pass and parks a NOT_READY call ONLY if no event arrived
  // since — otherwise an event landing in the gap between the failing
  // poll and the park would cost the full park timeout (a missed-wakeup
  // race the 200 us cap used to paper over, one whole cap per chunk).
  std::atomic<uint64_t> rx_events{0};
  void rx_event() {
    rx_events.fetch_add(1, std::memory_order_release);
    rx_cv.notify_all();
  }

  // ----- exchmem -----
  uint32_t rd(uint32_t addr) {
    std::lock_guard<std::mutex> g(exch_mu);
    uint32_t v;
    std::memcpy(&v, exchmem.data() + addr, 4);
    return v;
  }
  void wr(uint32_t addr, uint32_t v) {
    std::lock_guard<std::mutex> g(exch_mu);
    std::memcpy(exchmem.data() + addr, &v, 4);
  }
  uint32_t tuning(uint32_t addr, uint32_t dflt) {
    uint32_t v = rd(addr);
    return v ? v : dflt;
  }

  // Parse the communicator table at comm_addr out of exchange memory
  // (layout: size, local_rank, then per rank 7 words of which word 6 is
  // the device index == global transport rank; communicator.py
  // exchmem_words). comm_addr 0 means the full transport world.
  // Membership is derived from the device-index column so each rank's
  // exchmem copy needs no rank-specific local_rank word.
  //
  // Wire-format note: like the reference 64 B header (eth_intf.h:94-151),
  // eager frames carry (src, tag, seqn) but no communicator id, so
  // OVERLAPPING communicators must use distinct tags for concurrent
  // traffic on a shared link — the same discipline the reference
  // firmware's rxbuf seek (tag, src, seqn) matching requires. Disjoint
  // groups never share links and need no care.
  bool resolve_comm(uint32_t comm_addr, CommView &cm) {
    cm.map.clear();
    if (comm_addr == 0) {
      cm.world = world;
      cm.rank = rank;
      return true;
    }
    if (comm_addr % 4 != 0 || (uint64_t)comm_addr + 4 > EXCHMEM_BYTES)
      return false;
    uint32_t size = rd(comm_addr);
    if (size == 0 || size > world) return false;
    if ((uint64_t)comm_addr + 4ull * (2 + 7ull * size) > EXCHMEM_BYTES)
      return false;
    cm.map.resize(size);
    cm.rank = UINT32_MAX;
    bool ident = (size == world);
    uint64_t seen = 0;  // duplicate-member bitmap (world <= 64 in practice;
                        // larger worlds fall back to the O(n^2) scan)
    for (uint32_t i = 0; i < size; i++) {
      uint32_t dev = rd(comm_addr + 4 * (2 + 7 * i + 6));
      if (dev >= world) return false;
      if (dev < 64) {
        if (seen & (1ull << dev)) return false;  // duplicate member
        seen |= 1ull << dev;
      } else {
        for (uint32_t j = 0; j < i; j++)
          if (cm.map[j] == dev) return false;
      }
      cm.map[i] = dev;
      if (dev == rank) cm.rank = i;
      if (dev != i) ident = false;
    }
    if (cm.rank == UINT32_MAX) return false;  // caller not a member
    cm.world = size;
    if (ident) cm.map.clear();
    return true;
  }

  // ----- transport -----

  // Local-POE ingress: the SENDER's thread runs this against the
  // receiving runtime (no rx threads exist in local mode). The caller
  // holds none of ITS OWN locks (every frame_out site releases first),
  // so taking this runtime's rx/rndzv locks cannot deadlock.
  // ----- PoeSink: inbound frames from the transport seam ------------------

  // One inbound frame. Mem-backed bodies (datagram / in-process POEs)
  // arrive whole; stream bodies (TCP) expose the link so payloads land
  // directly at their destination.
  bool on_frame(uint32_t lane, const MsgHeader &h,
                acclw::PayloadSource &body) override {
    if (body.data()) return on_frame_mem(lane, h, body.data(), body.remaining());
    return on_frame_stream(lane, h, body);
  }

  // Memory-resident frame (the whole payload arrived with the header):
  // the merged landing path of the in-process and datagram POEs. The
  // stream POE never produces mem-backed bodies (on_frame dispatches on
  // body.data()), so tcp rx roles cannot enter.  // ACCL_POE(udp,local)
  bool on_frame_mem(uint32_t lane, const MsgHeader &h, const uint8_t *payload,
                    size_t plen) {
    if (stop.load()) return false;
    uint32_t s = sid(h.src, lane);
    // rx volume counts PRE-CRC on every transport (the acclrt.h
    // contract: a lossy link shows frames ARRIVING, damaged or not)
    if (h.msg_type == MSG_EGR_DATA) stat_rx_frames++;
    // dead host semantics for the in-process POE: frames into a wedged
    // rank are swallowed (never landed, never blocking the sender)
    if (local_mode && killed.load(std::memory_order_relaxed)) return true;
    if (rely_wire) {
      auto t0 = std::chrono::steady_clock::now();
      bool okc = h.crc == frame_crc(h, payload, plen);
      stat_rely_ns += (uint64_t)std::chrono::duration_cast<
          std::chrono::nanoseconds>(std::chrono::steady_clock::now() - t0)
          .count();
      if (!okc) {
        // corrupt frame: counted and DROPPED before any state is
        // touched — never landed. An eager drop leaves a seqn gap the
        // nack path repairs like a loss.
        stat_crc_drops++;
        if (h.msg_type == MSG_EGR_DATA &&
            !killed.load(std::memory_order_relaxed)) {
          std::lock_guard<std::mutex> g(rx_mu);
          note_want_locked(s, /*proven=*/true);
        }
        return true;
      }
    }
    switch (h.msg_type) {
      case MSG_HELLO:
        // datagram bring-up solicit (hello traffic has no meaning
        // in-process — the local POE's registry IS its bring-up)
        if (udp_mode) frame_out(h.src, MSG_HELLO_ACK, 0, 0, 0, 0, nullptr, 0);
        [[fallthrough]];
      case MSG_HELLO_ACK:
        if (udp_mode) {
          std::lock_guard<std::mutex> g(hello_mu);
          hello_seen[h.src] = true;
          hello_cv.notify_all();
        }
        return true;
      case MSG_ACK:
        if (!killed.load(std::memory_order_relaxed))
          handle_ack(h.src, lane, h.seqn);
        return true;
      case MSG_NACK:
        if (!killed.load(std::memory_order_relaxed))
          handle_nack(h.src, lane, h.seqn);
        return true;
      case MSG_EGR_DATA: {
        if (killed.load(std::memory_order_relaxed)) return true;  // dead host
        {
          // direct landing (zero-copy for the consumer): same
          // eligibility as the stream POE's rx path, but the copy
          // happens right here under rx_mu — in-process memcpy, no
          // staging. (Landings register only on ordered links, so the
          // datagram POE never matches one.)
          std::lock_guard<std::mutex> lk(rx_mu);
          auto lnd = eager_landings.find(s);
          if (lnd != eager_landings.end() && !lnd->second.in_use &&
              !lnd->second.abort && h.seqn == inbound_seq[s] &&
              src_valid_count[s] == 0 && !rx_drain_srcs.count(s) &&
              (lnd->second.tag == TAG_ANY || h.tag == TAG_ANY ||
               lnd->second.tag == h.tag) &&
              h.msg_bytes == lnd->second.want &&
              h.msg_off == lnd->second.landed &&
              h.bytes <= lnd->second.want - lnd->second.landed) {
            if (plen)
              std::memcpy(lnd->second.base + lnd->second.landed, payload,
                          plen);
            lnd->second.landed += plen;
            inbound_seq[s] = h.seqn + 1;
            rx_event();
            return true;
          }
        }
        std::vector<uint8_t> copy(payload, payload + plen);
        if (!land_eager(h, lane, std::move(copy), /*allow_grow=*/true))
          return false;
        return true;
      }
      case MSG_RNDZV_ADDR: {
        if (udp_mode) break;  // rendezvous not offered on the datagram POE
        {
          std::lock_guard<std::mutex> g(rndzv_mu);
          addr_q.push_back({h.src, h.vaddr, h.bytes, h.tag,
                            wire_host(h.host)});
          rndzv_cv.notify_all();
        }
        rx_event();
        return true;
      }
      case MSG_RNDZV_WRITE: {
        if (udp_mode) break;
        // validate + land + complete in one critical section (the
        // staged stream path's semantics; in-process the copy IS direct)
        bool posted = false;
        {
          std::lock_guard<std::mutex> g(rndzv_mu);
          for (auto it = posted_addrs.begin(); it != posted_addrs.end();
               ++it) {
            if (it->vaddr == h.vaddr && it->src == h.src &&
                it->bytes == h.bytes && !it->in_use && !it->abort) {
              if (plen)
                std::memcpy((void *)(uintptr_t)h.vaddr, payload, plen);
              posted_addrs.erase(it);
              done_q.push_back({h.src, h.vaddr, h.bytes, h.tag});
              rndzv_cv.notify_all();
              posted = true;
              break;
            }
          }
        }
        if (posted) rx_event();
        // unposted/revoked: dropped (late-write semantics), counted
        if (!posted) stat_rndzv_drops++;
        return true;
      }
      default:
        return true;
    }
    // rendezvous message on the sessionless POE: one-sided writes need
    // a session transport (reference: RDMA-only message types) — drop
    if (debug_on)
      fprintf(stderr, "[r%u] drop mt=%u on datagram transport\n", rank,
              h.msg_type);
    return true;
  }

  // Raw-frame emit: POE delivery of ONE serialized frame (header +
  // payload contiguous, CRC already set; dst and lane ride the header).
  // The retransmit path, the reorder-hold release, and the duplicate
  // injection all ride this, so a resent frame is byte-identical to
  // the original.
  bool wire_emit(const FrameBuf &f) {
    FrameView v = frame_view(f);
    return poe_send(v.h.dst, wire_lane(v.h), &v, 1);
  }

  // Every outbound frame funnels here. Debug-build invariant of the
  // vectored wire (the no-double-copy contract): the transport ships
  // borrowed scatter-gather views — payload_copies() counts
  // transport-side coalescing and stays zero except under the
  // ACCL_RT_WIRE_LEGACY cost model.
  bool poe_send(uint32_t dst, uint32_t lane, const FrameView *fv, size_t n) {
    if (stop.load()) return false;
    bool ok = poe->send_frames(dst, lane, fv, n);
    assert(legacy_wire || poe->payload_copies() == 0);
    return ok;
  }

  // Cumulative ack from a peer: everything below `upto` landed there —
  // release the retained frames of that (peer, lane) stream.
  void handle_ack(uint32_t src, uint32_t lane, uint32_t upto) {
    stat_ack_rx++;
    std::lock_guard<std::mutex> g(rely_mu);
    uint32_t s = sid(src, lane);
    if (s >= retx.size()) return;
    RetxBuf &rb = retx[s];
    while (!rb.q.empty() && (int32_t)(rb.q.front().seqn - upto) < 0) {
      rb.bytes -= rb.q.front().bytes->size();
      rb.q.pop_front();
    }
  }

  // Selective-retransmit request: queue the retained frame for the
  // HEALTH thread to resend verbatim (never a blocking data-frame send
  // on the rx thread that received the nack — see retx_pending). A seqn
  // already evicted from the bounded buffer is unrecoverable at this
  // layer (counted; the receiver's deadline owns it); a seqn the sender
  // has not produced yet is a benign receiver probe (a parked recv
  // nacking a head the sender is still computing) and is ignored.
  void handle_nack(uint32_t src, uint32_t lane, uint32_t seqn) {
    stat_nack_rx++;
    if (killed.load(std::memory_order_relaxed)) return;
    FramePtr f;
    bool evicted = false;
    {
      std::lock_guard<std::mutex> g(rely_mu);
      uint32_t s = sid(src, lane);
      if (s >= retx.size()) return;
      RetxBuf &rb = retx[s];
      for (auto &rf : rb.q)
        if (rf.seqn == seqn) {
          f = rf.bytes;
          break;
        }
      if (!f && !rb.q.empty() && (int32_t)(seqn - rb.q.front().seqn) < 0)
        evicted = true;
      if (f) {
        // dedup: a re-nack arriving before the pending resend went out
        // must not queue the same frame twice
        for (auto &p : retx_pending)
          if (p == f) {
            f = nullptr;
            break;
          }
        if (f) retx_pending.push_back(f);
      }
    }
    if (evicted) {
      stat_retx_miss++;
      if (debug_on)
        fprintf(stderr, "[r%u] NACK miss peer=%u seqn=%u (evicted)\n",
                rank, src, seqn);
    }
  }

  // Record that a consumer is provably waiting on (stream sid, inbound
  // head): the health thread turns a persistent want into
  // bounded-backoff NACKs. `proven` (a CRC drop, or stray seqns queued
  // behind the gap) nacks after ~1 ms; a bare miss may just be a
  // not-yet-sent head (or a frame mid-flight behind a scheduler stall)
  // and waits ~8 ms first — the sender ignores a nack for a seqn it
  // has not produced, but a nack for one already in flight costs a
  // spurious retransmit+dup, so the bare-miss delay is deliberately
  // above ordinary host jitter. rx_mu held by the caller.
  // ACCL_REQUIRES(rx_mu)
  void note_want_locked(uint32_t s, bool proven = false) {
    if (!rely_wire || s >= want.size()) return;
    WantState &w = want[s];
    uint32_t sq = inbound_seq[s];
    if (w.active && w.seqn == sq) return;
    w.active = true;
    w.seqn = sq;
    w.attempts = 0;
    bool fast = proven || src_valid_count[s] > 0;
    w.next_nack = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(fast ? 1 : 8);
  }

  // Reliability health thread (1 ms tick): sends the pending cumulative
  // acks and bounded-backoff nacks the rx state asks for, and releases
  // reorder-held tail frames. All sends happen with no rx/rely lock
  // held. A wedged rank's health thread goes silent with the rest of
  // its wire.
  void rely_loop() {
    while (!stop.load()) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      if (stop.load()) return;
      if (killed.load(std::memory_order_relaxed)) continue;
      // NOTE: the tick's own scan is NOT charged to rely_ns — it runs
      // on this background thread, off every dispatch's critical path;
      // the control frames it emits still pay their timed CRC in
      // frame_out, which is the cost the chaos gate budgets.
      auto t0 = std::chrono::steady_clock::now();
      struct Ctl {
        uint32_t s;  // stream sid — dst = s / n_lanes, lane = s % n_lanes
        MsgType mt;
        uint32_t seqn;
      };
      std::vector<Ctl> ctl;
      {
        std::lock_guard<std::mutex> g(rx_mu);
        for (uint32_t s = 0; s < world * n_lanes; s++) {
          if (s / n_lanes == rank) continue;
          WantState &w = want[s];
          if (w.active && inbound_seq[s] != w.seqn)
            w.active = false;  // repaired (or advanced past)
          if (w.active && t0 >= w.next_nack) {
            if (w.attempts >= nack_max) {
              // nack budget exhausted: the frame is unrecoverable at
              // this layer — deactivate and let the call deadline
              // surface it (a later seek miss re-arms a fresh cycle,
              // so the chatter stays bounded by the backoff sum)
              w.active = false;
            } else {
              ctl.push_back({s, MSG_NACK, w.seqn});
              w.attempts++;
              uint64_t ms = std::min<uint64_t>(
                  1ull << std::min(w.attempts, 6u), 50);
              w.next_nack = t0 + std::chrono::milliseconds(ms);
            }
          }
          uint32_t in = inbound_seq[s];
          if (in != acked_upto[s] &&
              (in - acked_upto[s] >= 32 ||
               t0 - last_ack_t[s] >= std::chrono::milliseconds(5))) {
            ctl.push_back({s, MSG_ACK, in});
            acked_upto[s] = in;
            last_ack_t[s] = t0;
          }
        }
      }
      // release reorder holds older than ~2 ms (a held TAIL frame has
      // no follower to swap with — the nack path would recover it, but
      // releasing here keeps the common case one round trip cheaper)
      // and drain the peers' queued retransmit requests
      std::vector<FramePtr> rel;
      {
        std::lock_guard<std::mutex> g(rely_mu);
        for (auto it = reorder_held.begin(); it != reorder_held.end();) {
          if (t0 - it->second.since >= std::chrono::milliseconds(2)) {
            rel.push_back(it->second.bytes);
            it = reorder_held.erase(it);
          } else {
            ++it;
          }
        }
        while (!retx_pending.empty()) {
          rel.push_back(retx_pending.front());
          retx_pending.pop_front();
          stat_retx_sent++;
        }
      }
      for (auto &c : ctl)
        frame_out(c.s / n_lanes, c.mt, 0, c.seqn, 0, 0, nullptr, 0,
                  /*host=*/0, /*msg_bytes=*/0, /*msg_off=*/0,
                  /*lane=*/c.s % n_lanes);
      for (auto &r : rel) wire_emit(*r);
    }
  }

  // A sender-side frame batch to one (dst, lane): views accumulate and
  // flush as ONE scatter-gather send_frames call — many small frames
  // per writev/sendmmsg, the syscall-floor cut for the tiny-message
  // regime. `keep` pins serialized rely frames until the flush ships
  // them (retx-budget eviction must not free a frame the batch still
  // references); non-rely views borrow the caller's payload, which
  // egr_send keeps alive through its final flush.
  struct TxBatch {
    uint32_t dst = 0, lane = 0;
    std::vector<FrameView> views;
    std::vector<FramePtr> keep;
    size_t bytes = 0;
  };
  static constexpr size_t TX_BATCH_FRAMES = 256;     // one writev's worth
  static constexpr size_t TX_BATCH_BYTES = 4u << 20;
  bool flush_batch(TxBatch &b) {
    if (b.views.empty()) return true;
    bool ok = poe_send(b.dst, b.lane, b.views.data(), b.views.size());
    b.views.clear();
    b.keep.clear();
    b.bytes = 0;
    return ok;
  }

  bool frame_out(uint32_t dst, MsgType mt, uint32_t tag, uint32_t seqn,
                 uint64_t bytes, uint64_t vaddr, const void *payload,
                 size_t payload_len, uint32_t host = 0,
                 uint64_t msg_bytes = 0, uint64_t msg_off = 0,
                 uint32_t lane = 0, TxBatch *batch = nullptr) {
    // a wedged rank's wire is dark: outbound frames vanish before the
    // transport (bring-up hellos stay exempt so a pre-armed kill can
    // never wedge a PEER's creation barrier)
    if (killed.load(std::memory_order_relaxed) && mt != MSG_HELLO &&
        mt != MSG_HELLO_ACK)
      return true;
    MsgHeader h{};
    h.magic = MSG_MAGIC;
    h.msg_type = mt;
    h.src = rank;
    h.dst = dst;
    h.tag = tag;
    h.seqn = seqn;
    h.host = wire_pack_host(host, lane);
    h.bytes = bytes;
    h.vaddr = vaddr;
    h.msg_bytes = msg_bytes;
    h.msg_off = msg_off;
    if (rely_wire) {
      auto t0 = std::chrono::steady_clock::now();
      h.crc = frame_crc(h, payload, payload_len);
      stat_rely_ns += (uint64_t)std::chrono::duration_cast<
          std::chrono::nanoseconds>(std::chrono::steady_clock::now() - t0)
          .count();
      if (mt == MSG_ACK) stat_ack_sent++;
      if (mt == MSG_NACK) stat_nack_sent++;
    }
    if (mt == MSG_EGR_DATA) stat_tx_frames++;
    if (rely_wire && mt == MSG_EGR_DATA) {
      // serialize once: the same bytes feed the retransmit buffer, the
      // TX batch, and the wire, so a NACK replays the frame verbatim —
      // retention is BY REFERENCE, never a second payload copy
      auto f = std::make_shared<FrameBuf>(sizeof h + payload_len);
      std::memcpy(f->data(), &h, sizeof h);
      if (payload_len)
        std::memcpy(f->data() + sizeof h, payload, payload_len);
      {
        std::lock_guard<std::mutex> g(rely_mu);
        RetxBuf &rb = retx[sid(dst, lane)];
        rb.q.push_back({seqn, f});
        rb.bytes += f->size();
        while (rb.bytes > retx_budget_bytes && rb.q.size() > 1) {
          rb.bytes -= rb.q.front().bytes->size();
          rb.q.pop_front();  // a nack for it will count retx_miss
        }
      }
      if (batch && tx_batch_on) {
        batch->views.push_back(frame_view(*f));
        batch->keep.push_back(f);
        batch->bytes += f->size();
        if (batch->views.size() >= TX_BATCH_FRAMES ||
            batch->bytes >= TX_BATCH_BYTES)
          return flush_batch(*batch);
        return true;
      }
      FramePtr wire = f;
      bool dup = false, hold = false;
      if (fault_pct_armed) {
        if (rng_u01() * 100.0 < fault_loss_pct) {
          stat_inj_loss++;
          return true;  // vanished on the wire (retx buffer keeps it)
        }
        if (rng_u01() * 100.0 < fault_corrupt_pct) {
          // flip one bit AFTER the CRC was computed, in a copy so the
          // retransmit buffer keeps the clean bytes. Payload bits when
          // there are any; the crc field itself on header-only frames —
          // framing fields stay intact either way, so the stream
          // survives and only the integrity check can catch it.
          auto bad = std::make_shared<FrameBuf>(*f);
          size_t off = payload_len
                           ? sizeof h + (size_t)(rng_u01() * payload_len)
                           : offsetof(MsgHeader, crc);
          if (off >= bad->size()) off = bad->size() - 1;
          (*bad)[off] ^= (uint8_t)(1u << (int)(rng_u01() * 8));
          wire = bad;
          stat_inj_corrupt++;
        }
        dup = rng_u01() * 100.0 < fault_dup_pct;
        hold = rng_u01() * 100.0 < fault_reorder_pct;
      }
      // REORDER: emit any previously-held frame AFTER this one (the
      // swap), or hold this one for the next frame to the same
      // (dst, lane) stream
      FramePtr released;
      {
        std::lock_guard<std::mutex> g(rely_mu);
        auto it = reorder_held.find(sid(dst, lane));
        if (it != reorder_held.end()) {
          released = it->second.bytes;
          reorder_held.erase(it);
        } else if (hold) {
          reorder_held[sid(dst, lane)] =
              HeldFrame{wire, std::chrono::steady_clock::now()};
          stat_inj_reorder++;
          wire = nullptr;
        }
      }
      bool ok = true;
      if (wire) {
        ok = wire_emit(*wire);
        if (ok && dup) {
          stat_inj_dup++;
          ok = wire_emit(*wire);
        }
      }
      if (released && ok) ok = wire_emit(*released);
      return ok;
    }
    if (batch && tx_batch_on && mt == MSG_EGR_DATA) {
      FrameView v;
      v.h = h;
      v.payload = (const uint8_t *)payload;
      v.payload_len = payload_len;
      batch->views.push_back(v);
      batch->bytes += sizeof h + payload_len;
      if (batch->views.size() >= TX_BATCH_FRAMES ||
          batch->bytes >= TX_BATCH_BYTES)
        return flush_batch(*batch);
      return true;
    }
    FrameView v;
    v.h = h;
    v.payload = (const uint8_t *)payload;
    v.payload_len = payload_len;
    return poe_send(dst, lane, &v, 1);
  }

  // depacketizer -> rxbuf enqueue/dequeue: land a segment in an IDLE slot
  // and publish the notification. Returns false on shutdown.
  //
  // allow_grow (datagram transport): the single rx thread must NEVER
  // block — a full ring would overflow the kernel socket buffer (silent
  // datagram loss surfacing as timeouts) and would starve bring-up
  // hello processing. The ring grows on demand up to a generous bound,
  // past which the blocking backpressure applies as a last resort.
  bool land_eager(const MsgHeader &h, uint32_t lane,
                  std::vector<uint8_t> payload, bool allow_grow = false) {
    uint32_t s = sid(h.src, lane);
    std::unique_lock<std::mutex> lk(rx_mu);
    size_t idx;
    if (!idle_q.empty()) {
      idx = idle_q.back();
      idle_q.pop_back();
    } else if (allow_grow && rx_slots.size() < (1u << 20)) {
      rx_slots.emplace_back();
      idx = rx_slots.size() - 1;
    } else {
      // last-resort backpressure past 2^20 slots: park the rx thread
      // until the sequencer frees a slot; stop wakes it, so teardown
      // cannot wedge (the alternative is dropping frames).
      // ACCL_ALLOW(ACCLN101: rx backpressure park past the 2^20-slot ring cap; woken by stop)
      rx_cv.wait(lk, [&] { return stop.load() || !idle_q.empty(); });
      if (stop.load()) return false;
      idx = idle_q.back();
      idle_q.pop_back();
    }
    if ((int32_t)(h.seqn - inbound_seq[s]) < 0) {
      // seqn already consumed: a LATE duplicate (datagram dup, or a
      // retransmit that crossed its own repair). Landing it would
      // leave a VALID slot no seek ever requests (leaked slot,
      // compaction disabled forever) — drop it, idempotently, and
      // COUNT it (the chaos soak reads the counter; stderr prints are
      // debug-gated so injected-dup storms never spam the console).
      stat_dup_drops++;
      if (debug_on)
        fprintf(stderr, "[r%u] land DROP late src=%u seqn=%u want=%u\n", rank,
                h.src, h.seqn, inbound_seq[s]);
      idle_q.push_back(idx);
      return true;
    }
    if (!rx_index.emplace(rx_key(s, h.seqn), idx).second) {
      // duplicate (sid, seqn): idempotent drop (a datagram duplicate,
      // an injected dup, or a retransmit racing the original) — the
      // first arrival wins
      stat_dup_drops++;
      idle_q.push_back(idx);
      return true;
    }
    RxSlot &slot = rx_slots[idx];
    slot.status = RxSlot::VALID;
    slot.src = h.src;
    slot.tag = h.tag;
    slot.seqn = h.seqn;
    slot.lane = lane;
    slot.msg_bytes = h.msg_bytes;
    slot.msg_off = h.msg_off;
    slot.t_land = std::chrono::steady_clock::now();
    slot.data = std::move(payload);
    src_valid_count[s]++;
    rx_event();
    return true;
  }

  // Poll-bounded pinned read shared by BOTH zero-copy landing paths
  // (eager landings and rendezvous one-sided writes): read `plen` bytes
  // from the stream body into `dest`, consulting `still_pinned()`
  // between 100 ms slices — when it reports the pin is gone
  // (revocation), the remainder diverts to scratch (the byte stream
  // must stay framed) and `ack_divert()` runs exactly once to release
  // the buffer and wake the bounded-waiting revoker. Returns false on
  // link death / stop; `*diverted_out` reports whether the payload was
  // consumed-to-void.
  bool pinned_read(PayloadSource &body, uint8_t *dest, size_t plen,
                   const std::function<bool()> &still_pinned,
                   const std::function<void()> &ack_divert,
                   bool *diverted_out) {
    std::vector<uint8_t> scratch;
    bool diverted = false;
    size_t off = 0;
    while (off < plen && !stop.load()) {
      int pr = body.poll_in(100);
      if (!diverted && !still_pinned()) {
        scratch.resize(plen);
        diverted = true;
        ack_divert();
      }
      if (pr <= 0) continue;
      uint8_t *tgt = diverted ? scratch.data() : dest;
      ssize_t r = body.read_avail(tgt + off, plen - off);
      if (r <= 0) {
        *diverted_out = diverted;
        return false;
      }
      off += (size_t)r;
    }
    *diverted_out = diverted;
    return off >= plen;
  }

  // One inbound frame from an ordered stream-POE link. The transport
  // already validated magic, src (the link's peer), and lane (the
  // link's lane); payload bytes are still ON THE WIRE behind `body`, so
  // the zero-copy landings read them straight into their destination.
  // Returning false drops the link (the transport's rx loop exits).
  bool on_frame_stream(uint32_t lane, const MsgHeader &h,
                       PayloadSource &body) {
    thread_local std::vector<uint8_t> payload;
    uint32_t s = sid(h.src, lane);
    // reliability control frames: header-only, verified and handled
    // inline (they never enter the seqn stream or the rx ring)
    if (h.msg_type == MSG_ACK || h.msg_type == MSG_NACK) {
      if (rely_wire && h.crc != frame_crc(h, nullptr, 0)) {
        stat_crc_drops++;
        return true;  // acks are cumulative, nacks retried: both survive
      }
      if (killed.load(std::memory_order_relaxed)) return true;
      if (h.msg_type == MSG_ACK)
        handle_ack(h.src, lane, h.seqn);
      else
        handle_nack(h.src, lane, h.seqn);
      return true;
    }
    if (h.msg_type == MSG_EGR_DATA) stat_rx_frames++;
    size_t plen = body.remaining();
    if (killed.load(std::memory_order_relaxed)) {
      // wedged rank: payload bytes are read off the link (the peer's
      // tx framing must not block on a dead consumer) and discarded —
      // nothing lands, nothing completes
      payload.resize(plen);
      if (plen && !body.read_exact(payload.data(), plen)) return false;
      return true;
    }
    // Direct placement: a registered landing whose message this
    // segment continues takes the payload straight off the wire
    // into the final buffer — no slot, no staging copy. Eligible only
    // when this segment is the next seqn with nothing queued before
    // it (the ordered link makes that exact). `in_use` pins the
    // destination across the unlocked read; revocation waits on it.
    if (h.msg_type == MSG_EGR_DATA && plen) {
      uint8_t *dest = nullptr;
      std::unique_lock<std::mutex> lk(rx_mu);
      auto lnd = eager_landings.find(s);
      if (lnd != eager_landings.end() && !lnd->second.in_use &&
          !lnd->second.abort &&
          h.seqn == inbound_seq[s] && src_valid_count[s] == 0 &&
          !rx_drain_srcs.count(s) &&
          (lnd->second.tag == TAG_ANY || h.tag == TAG_ANY ||
           lnd->second.tag == h.tag) &&
          h.msg_bytes == lnd->second.want &&
          h.msg_off == lnd->second.landed &&
          h.bytes <= lnd->second.want - lnd->second.landed) {
        lnd->second.in_use = true;
        dest = lnd->second.base + lnd->second.landed;
      }
      if (dest) {
        lk.unlock();
        bool diverted = false;
        bool ok = pinned_read(
            body, dest, plen,
            [&] {
              std::lock_guard<std::mutex> g(rx_mu);
              auto it2 = eager_landings.find(s);
              return it2 != eager_landings.end() && !it2->second.abort;
            },
            [&] {
              std::lock_guard<std::mutex> g(rx_mu);
              auto it2 = eager_landings.find(s);
              if (it2 != eager_landings.end()) it2->second.in_use = false;
              rx_cv.notify_all();
            },
            &diverted);
        // integrity check BEFORE the landing is published: the frame
        // was read straight into the consumer's buffer (in_use still
        // pins it), so a corrupt frame must not advance `landed` or
        // the inbound seqn — the bytes sit unobservable until the
        // retransmitted clean frame overwrites them, and the recv can
        // only ever complete with verified data ("never landed").
        bool crc_ok = true;
        if (ok && !diverted && rely_wire) {
          auto t0 = std::chrono::steady_clock::now();
          crc_ok = h.crc == frame_crc(h, dest, plen);
          stat_rely_ns += (uint64_t)std::chrono::duration_cast<
              std::chrono::nanoseconds>(std::chrono::steady_clock::now() -
                                        t0)
              .count();
        }
        lk.lock();
        lnd = eager_landings.find(s);  // may have been erased
        if (!diverted && lnd != eager_landings.end())
          lnd->second.in_use = false;
        if (!ok || stop.load()) {
          rx_cv.notify_all();
          return false;
        }
        if (!crc_ok) {
          stat_crc_drops++;
          note_want_locked(s, /*proven=*/true);
          rx_cv.notify_all();
          return true;
        }
        if (!diverted && lnd != eager_landings.end()) {
          lnd->second.landed += plen;
        } else if (diverted && h.msg_off + plen < h.msg_bytes) {
          // consumed-to-void mid-message: the rest of the dying
          // message is orphan tail whatever the revoker saw (it may
          // have observed landed == 0 and skipped arming)
          rx_drain_srcs.insert(s);
        }
        inbound_seq[s] = h.seqn + 1;
        rx_event();
        return true;
      }
    }
    // One-sided writes land DIRECTLY at the posted vaddr — the
    // zero-copy semantics the rendezvous protocol promises (the old
    // path staged through `payload` then memcpy'd). Same poll-bounded
    // pin/abort protocol as the eager landings: in_use pins the
    // target, revocation flips abort and the read diverts to scratch
    // within one 100 ms slice, so a timed-out caller's buffer is
    // never written after revocation returns.
    if (h.msg_type == MSG_RNDZV_WRITE && plen) {
      uint8_t *dest = nullptr;
      {
        std::lock_guard<std::mutex> g(rndzv_mu);
        for (auto &pa : posted_addrs) {
          if (pa.vaddr == h.vaddr && pa.src == h.src &&
              pa.bytes == h.bytes && !pa.in_use && !pa.abort) {
            pa.in_use = true;
            dest = (uint8_t *)(uintptr_t)h.vaddr;
            break;
          }
        }
      }
      if (dest) {
        // only ever invoked under rndzv_mu (pin-check / unpin /
        // completion scopes below)  // ACCL_REQUIRES(rndzv_mu)
        auto find_mine = [&]() -> RndzvAddr * {
          for (auto &pa : posted_addrs)
            if (pa.vaddr == h.vaddr && pa.src == h.src &&
                pa.bytes == h.bytes && pa.in_use)
              return &pa;
          return nullptr;
        };
        bool diverted = false;
        bool ok = pinned_read(
            body, dest, plen,
            [&] {
              std::lock_guard<std::mutex> g(rndzv_mu);
              RndzvAddr *pa = find_mine();
              return pa != nullptr && !pa->abort;
            },
            [&] {
              std::lock_guard<std::mutex> g(rndzv_mu);
              RndzvAddr *pa = find_mine();
              if (pa) pa->in_use = false;
              rndzv_cv.notify_all();
            },
            &diverted);
        // integrity check before the completion is published: a
        // corrupt one-sided write must not complete the recv (the
        // posting stays live, so a clean re-post/retry can still
        // land; rendezvous rides the session transport, so this is
        // the wire-corruption backstop, not a retransmit seam)
        bool crc_ok = true;
        if (ok && !diverted && rely_wire) {
          auto t0 = std::chrono::steady_clock::now();
          crc_ok = h.crc == frame_crc(h, dest, plen);
          stat_rely_ns += (uint64_t)std::chrono::duration_cast<
              std::chrono::nanoseconds>(std::chrono::steady_clock::now() -
                                        t0)
              .count();
          if (!crc_ok) stat_crc_drops++;
        }
        {
          std::lock_guard<std::mutex> g(rndzv_mu);
          RndzvAddr *pa = find_mine();
          if (pa) pa->in_use = false;
          if (!ok || stop.load()) {
            rndzv_cv.notify_all();
          } else if (!diverted && crc_ok && pa) {
            // completed write: consume the posting, publish completion
            for (auto it = posted_addrs.begin(); it != posted_addrs.end();
                 ++it) {
              if (&*it == pa) {
                posted_addrs.erase(it);
                break;
              }
            }
            done_q.push_back({h.src, h.vaddr, h.bytes, h.tag});
            rndzv_cv.notify_all();
          }
          // diverted: revoked mid-write — consumed-to-void, no
          // completion (the reference's late-write drop semantics)
        }
        if (!ok || stop.load()) return false;
        rx_event();
        return true;
      }
    }
    payload.resize(plen);
    if (plen && !body.read_exact(payload.data(), plen)) return false;
    if (rely_wire) {
      auto t0 = std::chrono::steady_clock::now();
      bool okc = h.crc == frame_crc(h, payload.data(), plen);
      stat_rely_ns += (uint64_t)std::chrono::duration_cast<
          std::chrono::nanoseconds>(std::chrono::steady_clock::now() -
                                    t0)
          .count();
      if (!okc) {
        // counted and dropped, never landed; an eager gap arms the
        // nack repair path
        stat_crc_drops++;
        if (h.msg_type == MSG_EGR_DATA) {
          std::lock_guard<std::mutex> g(rx_mu);
          note_want_locked(s, /*proven=*/true);
        }
        return true;
      }
    }
    switch (h.msg_type) {
      case MSG_EGR_DATA: {
        // allow_grow on the session transport too: the ring collectives
        // stream whole chunks as multi-segment messages, and a blocked
        // rx thread (ring full, sequencer mid-send) would stall the
        // socket into a ring-wide write deadlock. Growth is burst
        // absorption — the ring compacts once drained.
        if (!land_eager(h, lane, std::move(payload), /*allow_grow=*/true))
          return false;
        break;
      }
      case MSG_RNDZV_ADDR: {
        {
          std::lock_guard<std::mutex> g(rndzv_mu);
          addr_q.push_back({h.src, h.vaddr, h.bytes, h.tag,
                            wire_host(h.host)});
          rndzv_cv.notify_all();
        }
        rx_event();  // wake a parked sequencer waiting on the address
        break;
      }
      case MSG_RNDZV_WRITE: {
        // one-sided write: valid ONLY into an address this rank posted
        // to exactly this peer with exactly this size — otherwise any
        // connected peer would hold an arbitrary-write primitive into
        // the process. Unposted writes are dropped (and logged).
        // validate + land + complete in ONE critical section: a
        // completion timeout cannot slip between the posted-check and
        // the memcpy and free the target buffer underneath the write
        bool posted = false;
        {
          std::lock_guard<std::mutex> g(rndzv_mu);
          for (auto it = posted_addrs.begin(); it != posted_addrs.end();
               ++it) {
            if (it->vaddr == h.vaddr && it->src == h.src &&
                it->bytes == h.bytes) {
              posted_addrs.erase(it);
              posted = true;
              break;
            }
          }
          if (posted) {
            std::memcpy((void *)(uintptr_t)h.vaddr, payload.data(), plen);
            done_q.push_back({h.src, h.vaddr, h.bytes, h.tag});
            rndzv_cv.notify_all();
          }
        }
        if (posted) rx_event();  // wake a parked completion poll
        if (!posted) {
          // counted (stats2 rndzv_drops), printed only under
          // ACCL_RT_DEBUG: wire-drop logging must never spam stderr
          // on a revocation-heavy or chaos run
          stat_rndzv_drops++;
          if (debug_on)
            fprintf(stderr,
                    "[r%u] DROP unposted RNDZV_WRITE from r%u vaddr=%llx "
                    "bytes=%llu\n",
                    rank, h.src, (unsigned long long)h.vaddr,
                    (unsigned long long)h.bytes);
        }
        break;
      }
    }
    return true;
  }

  // ----- eager protocol (send .c:611-648 / recv .c:687-704) -----

  // seg_bytes 0 segments at the configured rx-buf size (the reference's
  // fixed rx-buffer geometry); the ring collectives pass a jumbo segment
  // for their streamed whole-chunk messages — receiver slots are growable
  // vectors, and on a CPU-bound host the per-segment syscall+header
  // overhead at 4 KB dominates the wire cost of a large chunk. Datagram
  // transport always respects the 64 KB packet ceiling.
  uint32_t egr_send(uint32_t dst, const uint8_t *ptr, uint64_t bytes,
                    uint32_t tag, uint64_t seg_bytes = 0) {
    // the datagram POE has no rendezvous path, so the configured message
    // ceiling applies to eager transfers there (without it, a huge send
    // would overflow the receiver's datagram buffer and surface as a
    // misleading sequencing error)
    if (udp_mode && bytes > max_rndzv) return DMA_SIZE_ERROR;
    if (fault_tail_pending.load(std::memory_order_acquire) &&
        fault_tail_dst.load(std::memory_order_relaxed) == dst) {
      // ACCL_RT_FAULT_DELAY_TAIL_MS precondition violated: delivering
      // more traffic to dst now would reorder the wire behind the
      // delayed tail — fail loudly at the source instead of producing a
      // baffling downstream sequencing error
      fprintf(stderr,
              "[r%u] FATAL: ACCL_RT_FAULT_DELAY_TAIL_MS wire-order "
              "violation: new eager traffic to r%u while its delayed "
              "tail is still in flight\n",
              rank, dst);
      abort();
    }
    uint64_t seg_max = seg_bytes ? seg_bytes : rx_buf_bytes;
    if (udp_mode) seg_max = std::min<uint64_t>(seg_max, rx_buf_bytes);
    // lane selection is per MESSAGE (every segment rides the same seqn
    // stream): bulk messages take the bulk lane so a jumbo in flight
    // cannot head-of-line-block a small message on lane 0
    uint32_t lane = lane_of(bytes);
    // one-shot fault arming: this message's final segment is delayed or
    // lost (see the fault-injection block above)
    bool fault_this = false;
    if ((fault_delay_tail_ms > 0 || fault_drop_tail) && bytes > seg_max &&
        !fault_armed.exchange(true))
      fault_this = true;
    TxBatch batch;
    batch.dst = dst;
    batch.lane = lane;
    uint64_t off = 0;
    while (off < bytes || bytes == 0) {
      uint64_t seg = std::min<uint64_t>(seg_max, bytes - off);
      uint32_t seqn = outbound_seq[sid(dst, lane)]++;
      bool last = (off + seg >= bytes);
      if (fault_this && last) {
        // tail levers run with batching off (tx_batch_on), but never
        // leave queued frames stranded behind the delayed/dropped tail
        if (!flush_batch(batch)) return RECEIVE_TIMEOUT_ERROR;
        if (fault_drop_tail) return NO_ERROR;  // lost on the wire
        // slow tail: deliver from a helper thread after the delay (the
        // caller must not send MORE traffic to dst before it lands, or
        // wire order breaks — acceptable for a test lever)
        std::vector<uint8_t> payload(ptr + off, ptr + off + seg);
        fault_tail_dst.store(dst, std::memory_order_relaxed);
        fault_tail_pending.store(true, std::memory_order_release);
        std::lock_guard<std::mutex> g(fault_mu);
        fault_threads.emplace_back([this, dst, tag, seqn, seg, bytes, off,
                                    lane,
                                    payload = std::move(payload)] {
          for (int waited = 0; waited < fault_delay_tail_ms && !stop.load();
               waited += 10)
            std::this_thread::sleep_for(std::chrono::milliseconds(10));
          if (!stop.load()) {
            // delivery-time wire-order assert: the arming contract says
            // nothing else advances this link while the tail is in
            // flight (egr_send aborts new eager traffic to dst above).
            // outbound_seq[dst] past seqn+1 here means a misconfigured
            // fault test already reordered the wire — fail fast and
            // loudly instead of delivering a tail that silently
            // corrupts the stream. The read is ordered by the
            // fault_tail_pending release/acquire pair: any egr_send
            // that could advance the counter observes pending==true
            // first and aborts, so a racing write cannot exist.
            // ACCL_ALLOW(ACCLN103: fault-thread read ordered by the fault_tail_pending release/acquire pair)
            if (outbound_seq[sid(dst, lane)] != seqn + 1) {
              fprintf(stderr,
                      "[r%u] FATAL: ACCL_RT_FAULT_DELAY_TAIL_MS wire-order "
                      "violation at delivery: outbound_seq[r%u]=%u advanced "
                      "past the delayed tail seqn=%u before the helper "
                      "thread delivered it\n",
                      // ACCL_ALLOW(ACCLN103: same release/acquire-ordered read, echoed in the abort message)
                      rank, dst, outbound_seq[sid(dst, lane)], seqn);
              abort();
            }
            frame_out(dst, MSG_EGR_DATA, tag, seqn, seg, 0, payload.data(),
                      seg, /*host=*/0, /*msg_bytes=*/bytes,
                      /*msg_off=*/off, lane);
          }
          fault_tail_pending.store(false, std::memory_order_release);
        });
        return NO_ERROR;
      }
      if (!frame_out(dst, MSG_EGR_DATA, tag, seqn, seg, 0, ptr + off, seg,
                     /*host=*/0, /*msg_bytes=*/bytes, /*msg_off=*/off, lane,
                     &batch))
        return RECEIVE_TIMEOUT_ERROR;
      off += seg;
      if (bytes == 0) break;  // zero-length notification (barrier)
    }
    // the caller's `ptr` (borrowed by non-rely batched views) stays
    // alive across this flush — the batch never outlives the call
    if (!flush_batch(batch)) return RECEIVE_TIMEOUT_ERROR;
    return NO_ERROR;
  }

  // Seek the segment matching (src, tag, expected seqn) with rx_mu HELD;
  // copy out (clamped to `cap`) + release (rxbuf_seek semantics). O(1)
  // via the (src, seqn) index. Returns NOT_READY when absent,
  // DMA_SIZE_ERROR on an oversized segment.
  //
  // Ordering faults are detected instead of wedging the link (reference
  // seqn-mismatch detection, dma_mover.cpp:342-352):
  //  - slots from src exist but the expected head seqn is absent: on the
  //    ordered per-link TCP transport this can never legally occur ->
  //    PACK_SEQ_NUMBER_ERROR; on the sessionless datagram POE the kernel
  //    may reorder under buffer pressure, so the expected datagram may
  //    still be in flight -> NOT_READY until the call deadline (loss
  //    surfaces as RECEIVE_TIMEOUT, not a misleading sequencing error);
  //  - `strict_tag`: an exact-tag mismatch AT the expected seqn is a
  //    protocol violation inside a collective (the head segment can never
  //    match) -> DMA_TAG_MISMATCH_ERROR. The non-strict SC_RECV retry
  //    path keeps NOT_READY there, because another parked recv with the
  //    matching tag may legally consume the head first.
  // ACCL_REQUIRES(rx_mu)
  uint32_t seek_locked(uint32_t src, uint32_t lane, uint32_t tag,
                       uint8_t *ptr, uint64_t cap, uint64_t *got,
                       bool strict_tag = false, bool msg_start = false,
                       uint64_t want_msg = 0) {
    uint32_t s_id = sid(src, lane);
    drain_orphans_locked(s_id);
    uint32_t want_seqn = inbound_seq[s_id];
    auto it = rx_index.find(rx_key(s_id, want_seqn));
    if (it == rx_index.end()) {
      // stray seqns with a missing head: on the bare ordered link this
      // can never legally occur (PACK_SEQ_NUMBER_ERROR); with the
      // reliability sublayer on it is exactly what a lost/corrupt/
      // reordered frame looks like MID-REPAIR — defer and let the nack
      // path fill the gap (note_want_locked arms it).
      if (src_valid_count[s_id] > 0 && !udp_mode && !rely_wire)
        return PACK_SEQ_NUMBER_ERROR;  // stray seqn on an ordered link
      stat_seek_miss++;
      note_want_locked(s_id);
      return NOT_READY;
    }
    stat_seek_hit++;
    size_t i = it->second;
    RxSlot &s = rx_slots[i];
    // Strict (collective) recvs meeting a MISMATCHED head (wrong tag or
    // message length) defer instead of erroring while the head may
    // legitimately belong to OTHER traffic interleaved on the link —
    // p2p messages share links with collective chunks, and the parked
    // p2p recv will consume its head and unblock ours (the reference's
    // rx pool matches out of order by (tag, src), so interleaved
    // traffic never faults there at all). Fail-fast is preserved for
    // provably stray heads: no outstanding recv pairs with it AND it
    // has sat unclaimed past the grace window.
    auto head_is_claimable = [&]() -> bool {
      auto age = std::chrono::steady_clock::now() - s.t_land;
      if (age < std::chrono::milliseconds(250)) return true;
      for (const auto &r : outstanding_recvs)
        if (r.src == src &&
            (r.tag == TAG_ANY || s.tag == TAG_ANY || r.tag == s.tag) &&
            r.bytes == s.msg_bytes)
          return true;
      return false;
    };
    if (!(tag == TAG_ANY || s.tag == tag || s.tag == TAG_ANY)) {
      if (strict_tag) {
        if (!head_is_claimable()) return DMA_TAG_MISMATCH_ERROR;
        note_defer_locked(s, tag, want_msg, DMA_TAG_MISMATCH_ERROR);
        return NOT_READY;
      }
      return NOT_READY;
    }
    // Message-boundary match at the head of a NEW message (msg_start):
    // the head segment must BE a message head (msg_off == 0) and its
    // total-message length must equal what this recv expects. Consuming a
    // shorter head message as "partial fill" of a larger recv would
    // concatenate two messages into one buffer; on the SC_RECV retry
    // path another parked recv with the matching length may legally
    // consume this head first, so defer with NOT_READY and let the
    // deadline turn an unmatched recv into RECEIVE_TIMEOUT; strict
    // recvs apply the claimable-head rule above.
    if (msg_start && (s.msg_bytes != want_msg || s.msg_off != 0)) {
      if (strict_tag) {
        if (!head_is_claimable()) return DMA_SIZE_ERROR;
        note_defer_locked(s, tag, want_msg, DMA_SIZE_ERROR);
        return NOT_READY;
      }
      return NOT_READY;
    }
    // Mid-message continuation must line up exactly with the progress the
    // resuming recv has already landed — anything else is a framing fault.
    if (!msg_start && (s.msg_bytes != want_msg || s.msg_off != want_msg - cap))
      return DMA_SIZE_ERROR;
    // A segment overflowing the remaining capacity after the message-level
    // match is a sender protocol fault (segments of one message must sum
    // to its msg_bytes) — an error in both modes.
    if (s.data.size() > cap) return DMA_SIZE_ERROR;
    *got = s.data.size();
    // empty vector's data() is null, and memcpy declares both pointers
    // nonnull even for zero sizes (UBSan: zero-length eager segments,
    // e.g. a world-strided chunk of a sub-world-sized buffer)
    if (ptr && !s.data.empty())
      std::memcpy(ptr, s.data.data(), s.data.size());
    release_slot_locked(i);
    rx_index.erase(it);
    src_valid_count[s_id]--;
    inbound_seq[s_id] = want_seqn + 1;
    rx_cv.notify_all();
    return NO_ERROR;
  }

  // Drain orphaned continuation segments (rx_mu held): when a recv dies
  // mid-message (deadline), the unconsumed tail of its message still
  // occupies the head seqns — discard segments until the next message
  // head (msg_off == 0) surfaces, then resume normal matching. Runs at
  // the top of seek AND before the SC_RECV elder-pairing check, so FIFO
  // eligibility is always judged against the true next message head.
  // ACCL_REQUIRES(rx_mu)
  void drain_orphans_locked(uint32_t s_id) {
    while (rx_drain_srcs.count(s_id)) {
      auto dit = rx_index.find(rx_key(s_id, inbound_seq[s_id]));
      if (dit == rx_index.end()) return;  // tail not yet arrived: stay armed
      RxSlot &ds = rx_slots[dit->second];
      if (ds.msg_off == 0) {
        rx_drain_srcs.erase(s_id);  // a fresh head: drain complete
        return;
      }
      release_slot_locked(dit->second);
      rx_index.erase(dit);
      src_valid_count[s_id]--;
      inbound_seq[s_id]++;
    }
  }

  // Return one slot to the IDLE free-list (rx_mu held), compacting a
  // grown ring back to the configured size once fully drained so one
  // burst does not permanently retain payload memory (all slots idle
  // implies the index is empty).
  // Reconfiguration fence (accl_rt_flush_rx): drop every landed-but-
  // unconsumed eager frame and advance the per-peer inbound seqn past
  // it, then clear the stale rendezvous queues. After a membership
  // change, frames of the OLD world's aborted collectives may sit in
  // the ring (per-op progress re-arms deadlines, so one survivor's
  // wedged call can outlive another's final send) — and the seqn-
  // ordered streamed matching would deliver them into the NEW world's
  // first recv as data. Caller contract: quiescent — no live calls on
  // this rank and peers' in-flight deliveries settled (the recovery
  // driver joins/barriers its survivors first); an in-flight frame
  // arriving after the fence carries a seqn below the advanced
  // inbound_seq and is dropped by land_eager's late-duplicate check.
  void flush_rx() {
    {
      std::lock_guard<std::mutex> g(rx_mu);
      for (size_t i = 0; i < rx_slots.size(); i++) {
        RxSlot &s = rx_slots[i];
        if (s.status != RxSlot::VALID) continue;
        uint32_t ss = sid(s.src, s.lane);
        if ((int32_t)(s.seqn + 1 - inbound_seq[ss]) > 0)
          inbound_seq[ss] = s.seqn + 1;
        rx_index.erase(rx_key(ss, s.seqn));
        src_valid_count[ss]--;
        release_slot_locked(i);  // may compact: the loop bound re-reads
      }
      rx_drain_srcs.clear();
      // reliability state is per-membership: a want armed for an
      // old-world gap must not nack into the new world, and the acked
      // watermark follows the advanced seqns so no ack ever regresses
      for (auto &w : want) w = WantState{};
      for (uint32_t s = 0; s < acked_upto.size(); s++)
        acked_upto[s] = inbound_seq[s];
      rx_cv.notify_all();
    }
    {
      // sender-side reliability state: retained frames and reorder
      // holds of the aborted old-world collectives are stale — a
      // post-fence nack can only reference post-fence traffic
      std::lock_guard<std::mutex> g(rely_mu);
      for (auto &rb : retx) {
        rb.q.clear();
        rb.bytes = 0;
      }
      retx_pending.clear();
      reorder_held.clear();
    }
    {
      std::lock_guard<std::mutex> g(rndzv_mu);
      addr_q.clear();
      done_q.clear();
      rndzv_cv.notify_all();
    }
  }

  // ACCL_REQUIRES(rx_mu)
  void release_slot_locked(size_t i) {
    RxSlot &s = rx_slots[i];
    s.status = RxSlot::IDLE;
    if (i >= base_rx_slots)
      std::vector<uint8_t>().swap(s.data);  // free burst capacity
    else
      s.data.clear();
    idle_q.push_back(i);
    if (rx_slots.size() > base_rx_slots &&
        idle_q.size() == rx_slots.size()) {
      rx_slots.resize(base_rx_slots);
      idle_q.clear();
      for (size_t j = 0; j < base_rx_slots; j++) idle_q.push_back(j);
    }
  }

  // ----- rendezvous protocol (.c:142-408) -----

  void rendezvous_send_addr(uint32_t dst, uint64_t vaddr, uint64_t bytes,
                            uint32_t tag, uint32_t host = 0) {
    {
      // register the posting BEFORE the peer can possibly write it
      std::lock_guard<std::mutex> g(rndzv_mu);
      posted_addrs.push_back({dst, vaddr, bytes, tag, host});
    }
    frame_out(dst, MSG_RNDZV_ADDR, tag, 0, bytes, vaddr, nullptr, 0, host);
  }

  // Non-blocking: waiting for a peer's address happens by NOT_READY
  // requeue in the sequencer, never inside this call.
  uint32_t rendezvous_get_addr(uint32_t src, uint64_t bytes, uint32_t tag,
                               uint64_t *vaddr) {
    std::lock_guard<std::mutex> lk(rndzv_mu);
    for (auto it = addr_q.begin(); it != addr_q.end(); ++it) {
      // wildcard on EITHER side matches, mirroring the eager seek's
      // (tag==ANY || slot==ANY || equal) rule: a TAG_ANY recv's posted
      // address must accept a tagged send (asymmetric wildcard — the
      // eager path always allowed it; the rendezvous matchers used to
      // honor the wildcard only on the send side)
      if (it->src == src && it->bytes == bytes &&
          (tag == TAG_ANY || it->tag == TAG_ANY || it->tag == tag)) {
        *vaddr = it->vaddr;
        addr_q.erase(it);
        return NO_ERROR;
      }
    }
    return NOT_READY;
  }

  uint32_t rendezvous_write(uint32_t dst, uint64_t remote_vaddr,
                            const uint8_t *ptr, uint64_t bytes, uint32_t tag) {
    return frame_out(dst, MSG_RNDZV_WRITE, tag, 0, bytes, remote_vaddr, ptr,
                     bytes)
               ? NO_ERROR
               : RECEIVE_TIMEOUT_ERROR;
  }

  // Drop the posting matching (src, vaddr, bytes, tag) — called with
  // rndzv_mu HELD on timeout/error revocation, so a late write cannot
  // land in a buffer the caller is about to free. Erases at most one
  // entry so other in-flight recvs keep their postings.
  // rndzv_mu held via lk. An in-flight direct write is asked to let go
  // (abort) and the wait is BOUNDED: the rx thread's read loop
  // re-checks the posting at least every 100 ms and acknowledges by
  // clearing in_use, diverting the rest of the payload to scratch — the
  // target buffer is never written after this returns. The cv wait
  // drops the lock, so the scan restarts after each wakeup.
  // ACCL_REQUIRES(rndzv_mu)
  void revoke_posted_locked(std::unique_lock<std::mutex> &lk, uint32_t src,
                            uint64_t vaddr, uint64_t bytes, uint32_t tag) {
    for (;;) {
      auto it = posted_addrs.begin();
      for (; it != posted_addrs.end(); ++it)
        if (it->src == src && it->vaddr == vaddr && it->bytes == bytes &&
            (tag == TAG_ANY || it->tag == tag))
          break;
      if (it == posted_addrs.end()) return;
      if (it->in_use) {
        it->abort = true;
        cv_wait_for(rndzv_cv, lk, std::chrono::milliseconds(250));
        continue;
      }
      posted_addrs.erase(it);
      return;
    }
  }

  // Non-blocking completion checks (the blocking variants are gone: every
  // receive dependency in the sequencer is NOT_READY-resumable, so waiting
  // happens by requeue, never inside a collective).
  uint32_t rndzv_completion_nb(uint32_t src, uint64_t vaddr, uint64_t bytes,
                               uint32_t tag) {
    std::lock_guard<std::mutex> lk(rndzv_mu);
    for (auto it = done_q.begin(); it != done_q.end(); ++it) {
      if (it->src == src && it->vaddr == vaddr && it->bytes == bytes &&
          (tag == TAG_ANY || it->tag == TAG_ANY || it->tag == tag)) {
        done_q.erase(it);
        return NO_ERROR;
      }
    }
    return NOT_READY;
  }

  // "Any" matching is scoped to the addresses THIS call posted: with
  // resumable state machines, two rendezvous collectives on disjoint
  // communicators can be in flight on one rank at once, and an unscoped
  // (bytes, tag) match would let one call consume the other's completion
  // and combine foreign data.
  uint32_t rndzv_any_posted_completion_nb(const std::deque<RndzvAddr> &posted,
                                          uint64_t bytes, uint32_t tag,
                                          uint32_t *src, uint64_t *vaddr) {
    std::lock_guard<std::mutex> lk(rndzv_mu);
    for (auto it = done_q.begin(); it != done_q.end(); ++it) {
      if (it->bytes != bytes ||
          !(tag == TAG_ANY || it->tag == TAG_ANY || it->tag == tag))
        continue;
      for (const auto &pa : posted) {
        if (pa.vaddr == it->vaddr && pa.src == it->src) {
          *src = it->src;
          *vaddr = it->vaddr;
          done_q.erase(it);
          return NO_ERROR;
        }
      }
    }
    return NOT_READY;
  }

  // (The eager/rendezvous split itself lives on Ops::rndzv, evaluated
  // against the per-call config snapshot. The datagram POE is eager-only:
  // rendezvous message types exist only on the RDMA stack in the
  // reference, eth_intf.h:42-45.)

  // ----- resumable op layer ----------------------------------------------
  // Every do_* below is a DETERMINISTIC sequence of ops (sends, receives,
  // rendezvous posts/completions, local mutations). Ops replays the
  // sequence on each (re-)entry: ops with index < current_step are skipped
  // (their side effects persist in caller memory or CollState), the op AT
  // current_step executes, and the first NOT_READY aborts the pass so the
  // sequencer requeues the call with current_step saved — the firmware
  // retry contract (ccl_offload_control.c:2308-2483) for EVERY collective,
  // not just SC_RECV. No receive dependency ever blocks the sequencer
  // thread; eager sends can still backpressure on a full TCP socket, as
  // the reference firmware does on a full TX FIFO.
  struct Ops {
    accl_rt &rt;
    Call &c;
    CollState &st;
    uint32_t tag;
    uint32_t idx = 0;

    template <class F> uint32_t op(F f) {
      uint32_t i = idx++;
      if (i < c.current_step) return NO_ERROR;  // replayed: already done
      uint32_t rc = f();
      if (rc == NO_ERROR) c.current_step = i + 1;
      return rc;
    }
    // protocol split from the per-call SNAPSHOT, not live config: a
    // config call between requeue passes must not shift the op sequence
    bool rndzv(uint64_t n) const { return !rt.udp_mode && n > st.max_eager; }
    // one-shot local mutation (scratch init, result memcpy): gated so a
    // resumed pass cannot clobber accumulated progress
    template <class F> void local(F f) {
      op([&] { f(); return (uint32_t)NO_ERROR; });
    }
    // eager or rendezvous send; the rendezvous address wait is NOT_READY
    // instead of blocking
    uint32_t send(uint32_t gdst, const uint8_t *p, uint64_t n) {
      return op([&]() -> uint32_t {
        if (rndzv(n)) {
          if (n > st.max_rndzv) return DMA_SIZE_ERROR;  // configured ceiling
          uint64_t va;
          uint32_t rc = rt.rendezvous_get_addr(gdst, n, tag, &va);
          if (rc != NO_ERROR) return rc;
          return rt.rendezvous_write(gdst, va, p, n, tag);
        }
        return rt.egr_send(gdst, p, n, tag);
      });
    }
    // post this rank's landing address (one-shot; tracked for timeout
    // revocation)
    uint32_t post(uint32_t gsrc, uint8_t *p, uint64_t n) {
      return op([&]() -> uint32_t {
        rt.rendezvous_send_addr(gsrc, (uint64_t)(uintptr_t)p, n, tag);
        st.posted.push_back({gsrc, (uint64_t)(uintptr_t)p, n, tag, 0});
        return NO_ERROR;
      });
    }
    uint32_t completion(uint32_t gsrc, uint8_t *p, uint64_t n) {
      return op([&]() -> uint32_t {
        uint32_t rc =
            rt.rndzv_completion_nb(gsrc, (uint64_t)(uintptr_t)p, n, tag);
        if (rc == NO_ERROR) st.unpost((uint64_t)(uintptr_t)p);
        return rc;
      });
    }
    // consume ANY completion landing in one of THIS call's postings, then
    // run fn(src, vaddr) inside the same op (reduce-root combines ride
    // here)
    template <class F> uint32_t any_completion_then(uint64_t n, F fn) {
      return op([&]() -> uint32_t {
        uint32_t s;
        uint64_t va;
        uint32_t rc =
            rt.rndzv_any_posted_completion_nb(st.posted, n, tag, &s, &va);
        if (rc != NO_ERROR) return rc;
        st.unpost(va);
        return fn(s, va);
      });
    }
    // eager or rendezvous receive. Eager lands segment-by-segment with
    // st.off tracking partial progress within the op; rendezvous posts
    // once (st.off as the posted marker) then polls the completion.
    // strict=false is the SC_RECV contract: a head-tag mismatch stays
    // NOT_READY because another parked recv may legally consume it.
    // force_eager: consume a message the peer is known to stream eagerly
    // regardless of size (the ring collectives' whole-chunk messages) —
    // the protocol split would otherwise post a rendezvous address for a
    // write that never comes.
    // ----- streamed whole-chunk helpers (the ring/tree internal hops) --
    // One logical chunk as eagerly-streamed jumbo-segment message(s):
    // on the session transport a single message (egr_send pipelines its
    // segments without waiting; the receiver drains incrementally inside
    // one resumable recv op); on the datagram POE the chunk splits into
    // messages <= max_rndzv — the configured datagram-mode message
    // ceiling — so large collectives no longer DMA_SIZE_ERROR there
    // (both sides compute the identical split from the snapshotted
    // config). Always paired: recv_stream on the peer, never a plain
    // recv/rendezvous post.
    uint64_t stream_cap(uint64_t n) const {
      return rt.udp_mode ? std::min<uint64_t>(st.max_rndzv, n ? n : 1) : n;
    }
    uint32_t send_stream(uint32_t gdst, const uint8_t *p, uint64_t n) {
      uint64_t cap = stream_cap(n);
      uint64_t off = 0;
      do {
        uint64_t m = n ? std::min<uint64_t>(cap, n - off) : 0;
        uint32_t rc = op([&, off = off, m = m] {
          return rt.egr_send(gdst, p + off, m, tag,
                             /*seg_bytes=*/STREAM_SEG_BYTES);
        });
        if (rc != NO_ERROR) return rc;
        off += m;
      } while (off < n);
      return NO_ERROR;
    }
    uint32_t recv_stream(uint32_t gsrc, uint8_t *p, uint64_t n) {
      uint64_t cap = stream_cap(n);
      uint64_t off = 0;
      do {
        uint64_t m = n ? std::min<uint64_t>(cap, n - off) : 0;
        uint32_t rc = recv(gsrc, p ? p + off : nullptr, m, /*strict=*/true,
                           /*force_eager=*/true);
        if (rc != NO_ERROR) return rc;
        off += m;
      } while (off < n);
      return NO_ERROR;
    }
    // protocol-aware pair: rendezvous keeps its one-sided write; the
    // eager side (any size in udp_mode, <= max_eager on sessions) rides
    // the streamed helpers so large datagram-transport collectives split
    // under the message ceiling instead of failing DMA_SIZE_ERROR
    uint32_t send_x(uint32_t gdst, const uint8_t *p, uint64_t n) {
      return rndzv(n) ? send(gdst, p, n) : send_stream(gdst, p, n);
    }
    uint32_t recv_x(uint32_t gsrc, uint8_t *p, uint64_t n) {
      return rndzv(n) ? recv(gsrc, p, n) : recv_stream(gsrc, p, n);
    }
    uint32_t recv(uint32_t gsrc, uint8_t *p, uint64_t n, bool strict = true,
                  bool force_eager = false) {
      return op([&]() -> uint32_t {
        if (!force_eager && rndzv(n)) {
          if (n > st.max_rndzv) return DMA_SIZE_ERROR;
          uint64_t va = (uint64_t)(uintptr_t)p;
          if (st.off == 0) {
            rt.rendezvous_send_addr(gsrc, va, n, tag);
            st.posted.push_back({gsrc, va, n, tag, 0});
            st.off = 1;  // posted marker
          }
          uint32_t rc = rt.rndzv_completion_nb(gsrc, va, n, tag);
          if (rc == NO_ERROR) {
            st.off = 0;
            st.unpost(va);
          }
          return rc;
        }
        if (rt.udp_mode && n > st.max_rndzv) return DMA_SIZE_ERROR;
        std::lock_guard<std::mutex> lk(rt.rx_mu);
        const void *tok = (const void *)&st;
        // the lane this message rides is a pure function of its size —
        // both ends compute it from the message length, so the receiver
        // watches exactly the seqn stream the sender feeds
        const uint32_t lane = rt.lane_of(n);
        const uint32_t lsid = rt.sid(gsrc, lane);
        // SC_RECV posted-order FIFO: outstanding p2p recvs register a
        // ticket (first execution follows run() order — the sequencer
        // starts fresh calls in queue order), and a recv may take a new
        // head message only when no EARLIER-posted outstanding recv
        // also pairs with it (tag match + exact message length). This
        // is the parked-notification FIFO contract: without it two
        // TAG_ANY recvs race through the retry queue and the head goes
        // to whichever retries first, not to the first posted. Register
        // BEFORE any defer below, or a pass bounced off the stream-owner
        // check would leave this call unticketed and a younger recv
        // could out-rank it.
        if (!strict && !st.ticketed) {
          st.ticket = rt.recv_ticket_next++;
          rt.outstanding_recvs.push_back({gsrc, tag, n, st.ticket, tok});
          st.ticketed = true;
        }
        // stream ownership: a call that consumed part of a multi-segment
        // message from gsrc owns the remainder — any other call defers,
        // or it would interleave payload mid-message
        auto ow = rt.rx_stream_owner.find(lsid);
        if (ow != rt.rx_stream_owner.end() && ow->second != tok)
          return NOT_READY;
        if (!strict) {
          if (st.off == 0) {
            // judge FIFO eligibility against the true next message head,
            // not an orphaned continuation segment awaiting drain
            rt.drain_orphans_locked(lsid);
            auto hit =
                rt.rx_index.find(rx_key(lsid, rt.inbound_seq[lsid]));
            if (hit != rt.rx_index.end()) {
              const RxSlot &hs = rt.rx_slots[hit->second];
              for (const auto &r : rt.outstanding_recvs)
                if (r.tok != tok && r.src == gsrc && r.ticket < st.ticket &&
                    (r.tag == TAG_ANY || hs.tag == TAG_ANY ||
                     r.tag == hs.tag) &&
                    r.bytes == hs.msg_bytes)
                  return NOT_READY;  // the elder recv pairs with this head
            }
          }
        }
        // Direct-placement sync: a registered landing accumulates
        // progress from the rx thread; fold it into st.off (which also
        // re-arms the call deadline) before falling through to the
        // slot path — segments that landed in slots while the landing
        // was ineligible (other traffic queued ahead) still merge here.
        auto itl = st.landing ? rt.eager_landings.find(lsid)
                              : rt.eager_landings.end();
        if (itl != rt.eager_landings.end() && itl->second.tok == tok)
          st.off = itl->second.landed;
        for (;;) {
          if (st.off >= n && n > 0) break;
          uint64_t got = 0;
          uint32_t rc = rt.seek_locked(gsrc, lane, tag,
                                       p ? p + st.off : nullptr, n - st.off,
                                       &got, strict,
                                       /*msg_start=*/st.off == 0,
                                       /*want_msg=*/n);
          if (rc != NO_ERROR) {  // NOT_READY keeps st.off progress
            if (rc == NOT_READY && st.off > 0 && st.off < n)
              rt.rx_stream_owner[lsid] = tok;  // mid-message: claim
            if (rc == NOT_READY && strict && !rt.udp_mode && p && n > 0 &&
                !st.landing &&
                rt.eager_landings.find(lsid) == rt.eager_landings.end() &&
                n >= (64ull << 10)) {
              // threshold: only chunks big enough that the saved
              // staging copy + slot allocation outweigh the
              // registration round trips (small logp hops measured
              // slower with landings at 2*rx_buf)
              // register direct placement for the remainder: the rx
              // thread reads our message's further segments straight
              // into p (rxbuf bypass; see EagerLanding)
              rt.eager_landings[lsid] =
                  EagerLanding{p, n, st.off, tag, /*in_use=*/false,
                               /*abort=*/false, tok};
              st.landing = true;
            }
            return rc;
          }
          st.off += got;
          if (itl != rt.eager_landings.end() && itl->second.tok == tok)
            itl->second.landed = st.off;  // keep the rx thread's
                                          // msg_off expectation exact
          if (st.off >= n) break;  // n == 0: one zero-length segment
        }
        if (st.landing) {
          auto drop = rt.eager_landings.find(lsid);
          if (drop != rt.eager_landings.end() && drop->second.tok == tok)
            rt.eager_landings.erase(drop);
          st.landing = false;
        }
        st.off = 0;
        auto own = rt.rx_stream_owner.find(lsid);
        if (own != rt.rx_stream_owner.end() && own->second == tok)
          rt.rx_stream_owner.erase(own);
        return NO_ERROR;
      });
    }
  };

  // ----- collective algorithms (firmware ports; cites in each) -----
  // All are replayed op sequences over Ops (see above): any nonzero return
  // aborts the pass — NOT_READY requeues with progress saved, real errors
  // complete the call.

  uint32_t do_bcast(Ops &o, const CommView &cm, uint8_t *buf, uint64_t bytes,
                    uint32_t root) {
    if (cm.world == 1) return NO_ERROR;
    uint32_t rc;
    if (o.rndzv(bytes) && cm.world > o.st.tun_bcast_ranks) {
      // binary distance-doubling tree (.c:814-867). `sender` flips on a
      // completed-or-replayed recv, so resumed passes recompute it.
      uint32_t l = (cm.rank + cm.world - root) % cm.world;
      bool sender = (cm.rank == root);
      uint32_t d = 1;
      while ((d << 1) <= cm.world - 1) d <<= 1;
      while (d > 0) {
        if (sender && l % (2 * d) == 0 && l + d < cm.world) {
          uint32_t peer = (l + d + root) % cm.world;
          if ((rc = o.send(cm.g(peer), buf, bytes))) return rc;
        } else if (!sender && l % d == 0 && l >= d && (l - d) % (2 * d) == 0) {
          uint32_t peer = (l - d + root) % cm.world;
          if ((rc = o.recv(cm.g(peer), buf, bytes))) return rc;
          sender = true;
        }
        d >>= 1;
      }
      return NO_ERROR;
    }
    // flat fan-out, eager or rendezvous (.c:868-988)
    if (cm.rank == root) {
      for (uint32_t i = 0; i < cm.world; i++)
        if (i != root && (rc = o.send_x(cm.g(i), buf, bytes))) return rc;
    } else {
      if ((rc = o.recv_x(cm.g(root), buf, bytes))) return rc;
    }
    return NO_ERROR;
  }

  uint32_t do_scatter(Ops &o, const CommView &cm, const uint8_t *src,
                      uint8_t *dst, uint64_t bytes, uint32_t root) {
    uint32_t rc;
    if (cm.rank == root) {
      for (uint32_t i = 0; i < cm.world; i++) {
        if (i == root) continue;
        if ((rc = o.send_x(cm.g(i), src + (uint64_t)i * bytes, bytes)))
          return rc;
      }
      o.local([&] { std::memcpy(dst, src + (uint64_t)root * bytes, bytes); });
    } else {
      if ((rc = o.recv_x(cm.g(root), dst, bytes))) return rc;
    }
    return NO_ERROR;
  }

  uint32_t do_gather(Ops &o, const CommView &cm, const uint8_t *src,
                     uint8_t *dst, uint64_t bytes, uint32_t root) {
    // eager: ring daisy-chain (.c:1206-1293); rendezvous: flat to root
    // (.c:1142-1204). The ring keeps per-link traffic constant.
    uint32_t rc;
    CollState &st = o.st;
    if (!o.rndzv(bytes)) {
      uint32_t nxt = cm.g((cm.rank + 1) % cm.world);
      uint32_t prv = cm.g((cm.rank + cm.world - 1) % cm.world);
      st.tmp.resize(bytes + 1);  // relay buffer survives requeues
      if (cm.rank == root) {
        o.local([&] { std::memcpy(dst + (uint64_t)root * bytes, src, bytes); });
        for (uint32_t s = 0; s < cm.world - 1; s++) {
          if ((rc = o.recv_stream(prv, st.tmp.data(), bytes))) return rc;
          uint32_t origin = (root + cm.world - 1 - s) % cm.world;
          o.local([&] {
            std::memcpy(dst + (uint64_t)origin * bytes, st.tmp.data(), bytes);
          });
        }
      } else {
        // relay: own data first, then forward everything originating
        // farther from root than us — world-1-dist(rank) messages, where
        // dist is the +1-direction hop count to root.
        if ((rc = o.send_stream(nxt, src, bytes))) return rc;
        uint32_t dist = (root + cm.world - cm.rank) % cm.world;
        for (uint32_t s = 0; s + 1 + dist < cm.world; s++) {
          if ((rc = o.recv_stream(prv, st.tmp.data(), bytes))) return rc;
          if ((rc = o.send_stream(nxt, st.tmp.data(), bytes))) return rc;
        }
      }
      return NO_ERROR;
    }
    // fan-in cap (accl.cpp:1200-1201 via the tuning registers, same rule
    // as plan.py gather selection): above the count threshold the flat
    // tree becomes a binomial combining tree. Any cap value below
    // world-1 selects the radix-2 binomial on BOTH executors (the XLA
    // gather_flat_schedule makes the identical binary choice), so the
    // register is a threshold switch, not a radix.
    uint32_t fanin = bytes > st.tun_gather_count
                         ? std::max(st.tun_gather_fanin, 1u)
                         : cm.world - 1;
    if (fanin < cm.world - 1) {
      // binomial: normalized rank l accumulates subtree chunks
      // [l, l + 2^k); children with l % 2d == d relay their block to
      // l - d chunk-by-chunk, so per-message size never exceeds what the
      // flat tree would send (the rendezvous ceiling applies per chunk).
      // The accumulation buffer holds only this rank's maximum subtree
      // (lowest set bit of l), not the full world, indexed relative to l.
      // `have` is recomputed by the replay as recv ops report done.
      uint32_t l = (cm.rank + cm.world - root) % cm.world;
      uint32_t max_have =
          l == 0 ? cm.world : std::min(l & (~l + 1), cm.world - l);
      st.acc.resize((uint64_t)max_have * bytes);
      o.local([&] { std::memcpy(st.acc.data(), src, bytes); });
      uint32_t have = 1;  // chunks accumulated at [l, l + have)
      for (uint32_t d = 1; d < cm.world; d <<= 1) {
        if (l % (2 * d) == d) {
          uint32_t parent = (l - d + root) % cm.world;
          for (uint32_t ci = 0; ci < have; ci++)
            if ((rc = o.send(cm.g(parent),
                             st.acc.data() + (uint64_t)ci * bytes, bytes)))
              return rc;
          return NO_ERROR;  // subtree delivered
        }
        if (l % (2 * d) == 0 && l + d < cm.world) {
          uint32_t child = (l + d + root) % cm.world;
          uint32_t n_ch = std::min(d, cm.world - (l + d));
          for (uint32_t ci = 0; ci < n_ch; ci++)
            if ((rc = o.recv(cm.g(child),
                             st.acc.data() + (uint64_t)(d + ci) * bytes,
                             bytes)))
              return rc;
          have += n_ch;
        }
      }
      // root (l == 0) de-normalizes chunk order into dst
      o.local([&] {
        for (uint32_t ln = 0; ln < cm.world; ln++) {
          uint32_t g = (ln + root) % cm.world;
          std::memcpy(dst + (uint64_t)g * bytes,
                      st.acc.data() + (uint64_t)ln * bytes, bytes);
        }
      });
      return NO_ERROR;
    }
    if (cm.rank == root) {
      o.local([&] { std::memcpy(dst + (uint64_t)root * bytes, src, bytes); });
      for (uint32_t i = 0; i < cm.world; i++) {
        if (i == root) continue;
        if ((rc = o.post(cm.g(i), dst + (uint64_t)i * bytes, bytes)))
          return rc;
      }
      for (uint32_t i = 0; i + 1 < cm.world; i++)
        if ((rc = o.any_completion_then(
                 bytes, [](uint32_t, uint64_t) { return (uint32_t)NO_ERROR; })))
          return rc;
    } else {
      if ((rc = o.send(cm.g(root), src, bytes))) return rc;
    }
    return NO_ERROR;
  }

  uint32_t do_allgather(Ops &o, const CommView &cm, const uint8_t *src,
                        uint8_t *dst, uint64_t bytes) {
    // Streamed-eager allgather at EVERY size (.c:1297-1499's role). The
    // former per-hop rendezvous handshake paid two extra wire round
    // trips per hop and measured SLOWER than the allreduce that moves
    // twice its bytes (emu_bench.csv r4: 0.023 vs 0.083 GB/s at
    // 1 MB / 8w); whole-chunk jumbo-segment streaming replaces it.
    //  - power-of-two worlds: recursive doubling — block sizes double
    //    every step, log2(P) latency steps instead of P-1. Before step
    //    d each rank holds the contiguous d-chunk block of its aligned
    //    group; partners' blocks are adjacent and merge.
    //  - other worlds: the ring, hop payloads streamed whole.
    uint32_t rc;
    o.local([&] { std::memcpy(dst + (uint64_t)cm.rank * bytes, src, bytes); });
    if ((cm.world & (cm.world - 1)) == 0 &&
        (shape_override == 2 ||
         (shape_override == 0 &&
          bytes * cm.world <= logp_ag_max_bytes(cm.world)))) {
      for (uint32_t d = 1; d < cm.world; d <<= 1) {
        uint32_t peer = cm.g(cm.rank ^ d);
        uint64_t mine = (uint64_t)(cm.rank & ~(d - 1)) * bytes;
        uint64_t theirs = (uint64_t)((cm.rank ^ d) & ~(d - 1)) * bytes;
        if ((rc = o.send_stream(peer, dst + mine, (uint64_t)d * bytes)))
          return rc;
        if ((rc = o.recv_stream(peer, dst + theirs, (uint64_t)d * bytes)))
          return rc;
      }
      return NO_ERROR;
    }
    uint32_t nxt = cm.g((cm.rank + 1) % cm.world);
    uint32_t prv = cm.g((cm.rank + cm.world - 1) % cm.world);
    const uint8_t *send_ptr = dst + (uint64_t)cm.rank * bytes;
    for (uint32_t s = 0; s < cm.world - 1; s++) {
      uint32_t origin = (cm.rank + cm.world - 1 - s) % cm.world;
      uint8_t *recv_ptr = dst + (uint64_t)origin * bytes;
      // eager sends before receives, socket buffering absorbing the
      // send so the ring cannot deadlock
      if ((rc = o.send_stream(nxt, send_ptr, bytes))) return rc;
      if ((rc = o.recv_stream(prv, recv_ptr, bytes))) return rc;
      send_ptr = recv_ptr;
    }
    return NO_ERROR;
  }

  uint32_t do_reduce(Ops &o, const CommView &cm, uint32_t dt, uint32_t func,
                     const uint8_t *src, uint8_t *dst, uint64_t count,
                     uint32_t root) {
    uint64_t bytes = count * dtype_bytes(dt);
    uint32_t rc;
    CollState &st = o.st;
    if (cm.world == 1) {
      o.local([&] { std::memcpy(dst, src, bytes); });
      return NO_ERROR;
    }
    if (!o.rndzv(bytes)) {
      // eager ring relay with fused recv-reduce-send (.c:1730-1743)
      uint32_t prv = cm.g((cm.rank + cm.world - 1) % cm.world);
      uint32_t nxt = cm.g((cm.rank + 1) % cm.world);
      uint32_t l = (cm.rank + cm.world - root) % cm.world;  // root at 0
      st.acc.resize(bytes + 1);
      o.local([&] { std::memcpy(st.acc.data(), src, bytes); });
      if (l != 1) {  // everyone except the chain head receives a partial
        if ((rc = o.recv_stream(prv, st.acc.data(), bytes))) return rc;
        if ((rc = o.op([&] {
               return combine_buffers(dt, func, st.acc.data(), src, count);
             })))
          return rc;
      }
      if (cm.rank != root) {
        if ((rc = o.send_stream(nxt, st.acc.data(), bytes))) return rc;
      } else {
        o.local([&] { std::memcpy(dst, st.acc.data(), bytes); });
      }
      return NO_ERROR;
    }
    // rendezvous: flat tree when small world/message, else binomial
    // (.c:1531-1727)
    bool flat = cm.world <= st.tun_reduce_ranks ||
                bytes <= st.tun_reduce_count;
    uint32_t l = (cm.rank + cm.world - root) % cm.world;
    if (flat) {
      if (cm.rank == root) {
        // landing slots must stay allocated (and un-moved) until every
        // posted write completes: st.acc persists across requeues
        st.acc.resize((uint64_t)(cm.world - 1) * bytes);
        for (uint32_t i = 0, j = 0; i < cm.world; i++) {
          if (i == root) continue;
          if ((rc = o.post(cm.g(i), st.acc.data() + (uint64_t)j * bytes,
                           bytes)))
            return rc;
          j++;
        }
        o.local([&] { std::memcpy(dst, src, bytes); });
        for (uint32_t i = 0; i + 1 < cm.world; i++)
          if ((rc = o.any_completion_then(bytes, [&](uint32_t, uint64_t va) {
                 return combine_buffers(dt, func, dst,
                                        (void *)(uintptr_t)va, count);
               })))
            return rc;
      } else {
        if ((rc = o.send(cm.g(root), src, bytes))) return rc;
      }
      return NO_ERROR;
    }
    // binomial combining tree: children l%2d==d send to parent l-d
    st.acc.resize(bytes);
    st.tmp.resize(bytes);
    o.local([&] { std::memcpy(st.acc.data(), src, bytes); });
    for (uint32_t d = 1; d < cm.world; d <<= 1) {
      if (l % (2 * d) == d) {
        uint32_t peer = (l - d + root) % cm.world;
        return o.send(cm.g(peer), st.acc.data(), bytes);  // subtree done
      }
      if (l % (2 * d) == 0 && l + d < cm.world) {
        uint32_t peer = (l + d + root) % cm.world;
        if ((rc = o.recv(cm.g(peer), st.tmp.data(), bytes))) return rc;
        if ((rc = o.op([&] {
               return combine_buffers(dt, func, st.acc.data(), st.tmp.data(),
                                      count);
             })))
          return rc;
      }
    }
    if (cm.rank == root)
      o.local([&] { std::memcpy(dst, st.acc.data(), bytes); });
    return NO_ERROR;
  }

  // Auto crossover between the log2(P)-hop recursive halving/doubling
  // shapes and the 2(P-1)/(P-1)-hop rings: the log shape saves
  // (hops_ring - hops_log) scheduling latencies but its larger per-hop
  // messages overlap worse on a contended host, so it wins only while
  // payloads are latency-dominated. Calibrated from the forced-shape
  // sweep (accl_log/rt_stats_shape_*.csv, tools/rt_stats_sweep.py
  // --shape): measured tie points sit at ~32 KB of payload per hop
  // saved (w8: tie ~256 KB with 8 hops saved; w16: tie ~512-700 KB
  // with 22 saved; allgather tie ~512 KB total with 4 saved).
  static uint32_t log2_floor(uint32_t world) {
    uint32_t r = 0;
    while ((1u << (r + 1)) <= world) r++;
    return r;
  }
  // allreduce: ring 2(P-1) hops vs halving-doubling 2*log2(P)
  uint64_t logp_max_bytes(uint32_t world) const {
    uint32_t hops_saved = 2 * (world - 1) - 2 * log2_floor(world);
    return (uint64_t)hops_saved * LOGP_ALLREDUCE_HOP_BYTES;
  }
  // allgather: ring P-1 hops vs doubling log2(P); threshold compares
  // against the TOTAL gathered payload (world * chunk)
  uint64_t logp_ag_max_bytes(uint32_t world) const {
    uint32_t hops_saved = (world - 1) - log2_floor(world);
    return (uint64_t)hops_saved * LOGP_ALLGATHER_HOP_BYTES;
  }

  uint32_t do_allreduce(Ops &o, const CommView &cm, uint32_t dt,
                        uint32_t func, const uint8_t *src, uint8_t *dst,
                        uint64_t count) {
    uint64_t eb = dtype_bytes(dt);
    uint64_t bytes = count * eb;
    uint32_t rc;
    CollState &st = o.st;
    if (cm.world == 1) {
      o.local([&] { std::memcpy(dst, src, bytes); });
      return NO_ERROR;
    }
    // Tuning-register escape hatch: rendezvous-size payloads up to the
    // ALLREDUCE_COMPOSITION register run the reference's reduce+bcast
    // composition (.c:1878-1887) — kept runtime-selectable (the
    // accl.cpp:1198-1208 posture) so the timing model can arbitrate
    // ring-vs-composition per (size, world); register 0 (default) keeps
    // the measured ring below.
    if (o.rndzv(bytes) && bytes <= st.tun_allred_comp) {
      if ((rc = do_reduce(o, cm, dt, func, src, dst, count, 0))) return rc;
      return do_bcast(o, cm, dst, bytes, 0);
    }
    // Two streamed-eager shapes, both moving the bandwidth-optimal
    // ~2*bytes*(P-1)/P per link (hop payloads are whole chunks as
    // jumbo-segment messages — egr_send pipelines rx-buf/jumbo segments
    // without waiting and the receiver drains them incrementally inside
    // one resumable recv op, the reference's >2-moves-in-flight posture
    // (.c:626-647) without a per-segment op explosion):
    //
    //  - power-of-two worlds: recursive vector halving-doubling
    //    (Rabenseifner) — the same volume in 2*log2(P) latency steps
    //    instead of the ring's 2(P-1). The emulator is scheduling-
    //    latency-bound (single-core CI hosts: each serialized hop pays a
    //    thread wakeup, ~0.5 ms measured — accl_log/rt_stats_*.csv), so
    //    critical-path hop count is what the wall clock sees; on real
    //    wires the same structure is the standard latency-optimal
    //    midsize allreduce.
    //  - other worlds: ring reduce-scatter + ring allgather
    //    (.c:1888-2071's shape).
    //
    // The rendezvous reduce+bcast composition (.c:1878-1887) measured 4x
    // slower than bcast alone at 1 MB / 8 ranks (emu_bench.csv), so it
    // rides the tuning register above instead of a size rule.
    bool pow2 = (cm.world & (cm.world - 1)) == 0;
    bool logp = pow2 && (shape_override == 2 ||
                         (shape_override == 0 &&
                          bytes <= logp_max_bytes(cm.world)));
    if (logp) {
      o.local([&] { std::memcpy(dst, src, bytes); });
      // phase 1: reduce-scatter by recursive halving. Pair (r, r^d)
      // splits the shared window; the rank with bit d clear keeps the
      // lower half. Windows are identical within every pair because
      // they depend only on decisions at higher bits.
      uint64_t lo = 0, hi = count;
      for (uint32_t d = cm.world >> 1; d >= 1; d >>= 1) {
        uint32_t peer = cm.g(cm.rank ^ d);
        uint64_t mid = lo + (hi - lo) / 2;
        uint64_t klo, khi, slo, shi;
        if ((cm.rank & d) == 0) {
          klo = lo; khi = mid; slo = mid; shi = hi;
        } else {
          klo = mid; khi = hi; slo = lo; shi = mid;
        }
        if ((rc = o.send_stream(peer, dst + slo * eb, (shi - slo) * eb)))
          return rc;
        st.tmp.resize((khi - klo) * eb + 1);  // +1: never moves for n=0
        if ((rc = o.recv_stream(peer, st.tmp.data(), (khi - klo) * eb)))
          return rc;
        if ((rc = o.op([&, klo = klo, khi = khi] {
               return combine_buffers(dt, func, dst + klo * eb,
                                      st.tmp.data(), khi - klo);
             })))
          return rc;
        lo = klo; hi = khi;
      }
      // phase 2: allgather by recursive doubling, merging sibling
      // windows in reverse split order. window_at(r, d) replays r's
      // halving decisions down to distance d — the pair's windows are
      // complementary halves of their common parent.
      auto window_at = [&](uint32_t r, uint32_t dstop) {
        uint64_t wlo = 0, whi = count;
        for (uint32_t d = cm.world >> 1; d >= dstop; d >>= 1) {
          uint64_t mid = wlo + (whi - wlo) / 2;
          if ((r & d) == 0) whi = mid; else wlo = mid;
        }
        return std::pair<uint64_t, uint64_t>(wlo, whi);
      };
      for (uint32_t d = 1; d < cm.world; d <<= 1) {
        uint32_t peer = cm.g(cm.rank ^ d);
        auto [plo, phi] = window_at(cm.rank ^ d, d);
        if ((rc = o.send_stream(peer, dst + lo * eb, (hi - lo) * eb)))
          return rc;
        if ((rc = o.recv_stream(peer, dst + plo * eb, (phi - plo) * eb)))
          return rc;
        lo = std::min(lo, plo);
        hi = std::max(hi, phi);
      }
      return NO_ERROR;
    }
    uint64_t bulk = (count + cm.world - 1) / cm.world;
    auto chunk = [&](uint32_t idx) {
      uint64_t lo = std::min<uint64_t>((uint64_t)idx * bulk, count);
      uint64_t hi = std::min<uint64_t>(lo + bulk, count);
      return std::pair<uint64_t, uint64_t>(lo, hi - lo);
    };
    o.local([&] { std::memcpy(dst, src, bytes); });
    uint32_t nxt = cm.g((cm.rank + 1) % cm.world);
    uint32_t prv = cm.g((cm.rank + cm.world - 1) % cm.world);
    st.tmp.resize(bulk * eb + 1);
    // reduce-scatter: hop s sends chunk (rank-1-s) — combined locally at
    // hop s-1 — and combines arriving chunk (rank-2-s), the same
    // derivation as schedules.reduce_scatter_ring
    for (uint32_t s = 0; s + 1 < cm.world; s++) {
      uint32_t sidx = (cm.rank + cm.world - 1 - s) % cm.world;
      uint32_t ridx = (cm.rank + 2 * cm.world - 2 - s) % cm.world;
      auto [slo, sn] = chunk(sidx);
      if ((rc = o.send_stream(nxt, dst + slo * eb, sn * eb))) return rc;
      auto [rlo, rn] = chunk(ridx);
      if ((rc = o.recv_stream(prv, st.tmp.data(), rn * eb))) return rc;
      if ((rc = o.op([&, rlo = rlo, rn = rn] {
             return combine_buffers(dt, func, dst + rlo * eb, st.tmp.data(),
                                    rn);
           })))
        return rc;
    }
    // ring allgather of reduced chunks: hop s relays chunk (rank-s),
    // receiving chunk (rank-1-s) directly into place
    for (uint32_t s = 0; s + 1 < cm.world; s++) {
      uint32_t sidx = (cm.rank + cm.world - s) % cm.world;
      uint32_t ridx = (cm.rank + cm.world - 1 - s) % cm.world;
      auto [slo, sn] = chunk(sidx);
      if ((rc = o.send_stream(nxt, dst + slo * eb, sn * eb))) return rc;
      auto [rlo, rn] = chunk(ridx);
      if ((rc = o.recv_stream(prv, dst + rlo * eb, rn * eb))) return rc;
    }
    return NO_ERROR;
  }

  uint32_t do_reduce_scatter(Ops &o, const CommView &cm, uint32_t dt,
                             uint32_t func, const uint8_t *src, uint8_t *dst,
                             uint64_t count) {
    // count = per-rank output elements; input holds world*count.
    uint64_t eb = dtype_bytes(dt);
    uint64_t bytes = count * eb;
    uint32_t rc;
    CollState &st = o.st;
    if (cm.world == 1) {
      o.local([&] { std::memcpy(dst, src, bytes); });
      return NO_ERROR;
    }
    if (o.rndzv(bytes)) {
      // reduce(count*world) to 0 then scatter (.c:1768-1781); st.full is
      // the composition's intermediate (do_reduce owns st.acc/st.tmp)
      st.full.resize((uint64_t)cm.world * bytes);
      if ((rc = do_reduce(o, cm, dt, func, src, st.full.data(),
                          (uint64_t)count * cm.world, 0)))
        return rc;
      return do_scatter(o, cm, st.full.data(), dst, bytes, 0);
    }
    // eager ring (.c:1782-1850), hop payloads streamed whole
    uint32_t nxt = cm.g((cm.rank + 1) % cm.world);
    uint32_t prv = cm.g((cm.rank + cm.world - 1) % cm.world);
    st.tmp.resize(bytes + 1);
    uint32_t cidx = (cm.rank + cm.world - 1) % cm.world;
    if ((rc = o.send_stream(nxt, src + (uint64_t)cidx * bytes, bytes)))
      return rc;
    for (uint32_t s = 0; s < cm.world - 1; s++) {
      uint32_t idx = (cm.rank + 2 * cm.world - 2 - s) % cm.world;
      if ((rc = o.recv_stream(prv, st.tmp.data(), bytes))) return rc;
      if ((rc = o.op([&] {
             return combine_buffers(dt, func, st.tmp.data(),
                                    src + (uint64_t)idx * bytes, count);
           })))
        return rc;
      if (s + 1 < cm.world - 1 &&
          (rc = o.send_stream(nxt, st.tmp.data(), bytes)))
        return rc;
    }
    o.local([&] { std::memcpy(dst, st.tmp.data(), bytes); });
    return NO_ERROR;
  }

  uint32_t do_alltoall(Ops &o, const CommView &cm, const uint8_t *src,
                       uint8_t *dst, uint64_t bytes) {
    // pairwise rotation exchange (.c:2140-2211)
    uint32_t rc;
    o.local([&] {
      std::memcpy(dst + (uint64_t)cm.rank * bytes,
                  src + (uint64_t)cm.rank * bytes, bytes);
    });
    bool rv = o.rndzv(bytes);
    for (uint32_t k = 1; k < cm.world; k++) {
      uint32_t to = (cm.rank + k) % cm.world;
      uint32_t from = (cm.rank + cm.world - k) % cm.world;
      uint8_t *rptr = dst + (uint64_t)from * bytes;
      if (rv) {
        // post our landing address before sending: every rank's step-k
        // target posted its own at step k, so no addr-wait cycle forms
        if ((rc = o.post(cm.g(from), rptr, bytes))) return rc;
        if ((rc = o.send(cm.g(to), src + (uint64_t)to * bytes, bytes)))
          return rc;
        if ((rc = o.completion(cm.g(from), rptr, bytes))) return rc;
      } else {
        if ((rc = o.send_stream(cm.g(to), src + (uint64_t)to * bytes,
                                bytes)))
          return rc;
        if ((rc = o.recv_stream(cm.g(from), rptr, bytes))) return rc;
      }
    }
    return NO_ERROR;
  }

  uint32_t do_barrier(Ops &o, const CommView &cm) {
    // zero-payload notification gather to 0 + fan-out (.c:2078-2120)
    uint32_t rc;
    if (cm.rank == 0) {
      for (uint32_t i = 1; i < cm.world; i++)
        if ((rc = o.recv(cm.g(i), nullptr, 0))) return rc;
      for (uint32_t i = 1; i < cm.world; i++)
        if ((rc = o.send(cm.g(i), nullptr, 0))) return rc;
    } else {
      if ((rc = o.send(cm.g(0), nullptr, 0))) return rc;
      if ((rc = o.recv(cm.g(0), nullptr, 0))) return rc;
    }
    return NO_ERROR;
  }

  // ----- sequencer main loop (run(), .c:2308-2483) -----

  // Compressed-domain execution (ETH_COMPRESSED on fp32 operands, the
  // default (float32,float16) arithconfig with arith_is_compressed=true,
  // arithconfig.hpp:102-119): cast operands to fp16 scratch, run the
  // whole collective at half wire width, cast the result back.
  uint32_t execute(Call &c) {
    // A wedged rank (accl_rt_kill / ACCL_RT_FAULT_KILL_RANK): every
    // call — in-flight retries included — terminates NOW with the
    // sticky RECEIVE_TIMEOUT word. The terminal path below records the
    // span, so the death leaves a final sticky-retcode record in the
    // trace ring for the host flight recorder to fire on.
    if (killed.load(std::memory_order_acquire)) {
      if (c.cstate) revoke_call_postings(c);
      return RECEIVE_TIMEOUT_ERROR;
    }
    // The firmware caches the communicator addressed by desc word 2 per
    // call (ccl_offload_control.c:2317-2372); malformed tables or calls
    // from a non-member rank fail descriptor decode. The resolved view
    // rides the Call so NOT_READY requeues skip the re-parse.
    if (!c.comm_resolved) {
      if (!resolve_comm(c.desc[2], c.comm)) return DMA_DECODE_ERROR;
      c.comm_resolved = true;
    }
    if (!c.cstate) c.cstate = std::make_shared<CollState>();
    if (!c.cstate->cfg) {
      CollState &st = *c.cstate;
      st.cfg = true;
      st.max_eager = max_eager;
      st.max_rndzv = max_rndzv;
      st.tun_bcast_ranks = tuning(BCAST_FLAT_TREE_MAX_RANKS, 3);
      st.tun_gather_fanin = tuning(GATHER_FLAT_TREE_MAX_FANIN, 2);
      st.tun_gather_count = tuning(GATHER_FLAT_TREE_MAX_COUNT, 32 * 1024);
      st.tun_reduce_ranks = tuning(REDUCE_FLAT_TREE_MAX_RANKS, 4);
      st.tun_reduce_count = tuning(REDUCE_FLAT_TREE_MAX_COUNT, 32 * 1024);
      st.tun_allred_comp = tuning(ALLREDUCE_COMPOSITION_MAX_COUNT, 0);
    }
    if (!c.deadline_set) {
      c.deadline = std::chrono::steady_clock::now() +
                   std::chrono::milliseconds(timeout_ms);
      c.deadline_set = true;
      std::lock_guard<std::mutex> g(rx_mu);
      c.defer0 = last_defer.count;
    }
    uint32_t step_before = c.current_step;
    uint64_t off_before = c.cstate->off;
    uint32_t rc = execute_guts(c);
    if (rc == NOT_READY) {
      // per-op timeout semantics (each blocking primitive used to get a
      // fresh timeout_ms budget): any progress re-arms the deadline, so
      // only a genuinely stalled op times the call out
      if (c.current_step != step_before || c.cstate->off != off_before) {
        c.deadline = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(timeout_ms);
        return rc;
      }
      if (std::chrono::steady_clock::now() > c.deadline) {
        if (debug_on)
          fprintf(stderr, "[r%u] call timeout scenario=%u step=%u\n", rank,
                  c.desc[0], c.current_step);
        {
          // a strict-recv head mismatch softened into a defer
          // (head_is_claimable) is the likeliest cause of an otherwise
          // bare timeout: echo the recorded mismatch so the protocol
          // fault stays diagnosable. Gated on defers recorded DURING
          // this call (> defer0) — an earlier call's resolved deferral
          // must not be reported as this timeout's cause.
          std::lock_guard<std::mutex> g(rx_mu);
          if (last_defer.count > c.defer0) {
            fprintf(stderr,
                    "[r%u] RECEIVE_TIMEOUT detail scenario=%u step=%u: "
                    "%llu deferred head mismatch(es); last from r%u "
                    "head(tag=%u seqn=%u msg=%llu off=%llu) vs "
                    "wanted(tag=%u msg=%llu) fault=%s(0x%x)\n",
                    rank, c.desc[0], c.current_step,
                    (unsigned long long)last_defer.count, last_defer.src,
                    last_defer.head_tag, last_defer.head_seqn,
                    (unsigned long long)last_defer.head_msg,
                    (unsigned long long)last_defer.head_off,
                    last_defer.want_tag,
                    (unsigned long long)last_defer.want_msg,
                    last_defer.code == DMA_TAG_MISMATCH_ERROR
                        ? "DMA_TAG_MISMATCH"
                        : last_defer.code == DMA_SIZE_ERROR
                              ? "DMA_SIZE_ERROR"
                              : "NONE",
                    last_defer.code);
            // the span drained through accl_rt_trace_read carries the
            // original fault code alongside the RECEIVE_TIMEOUT retcode
            c.trace_detail = last_defer.code;
          }
        }
        revoke_call_postings(c);
        return RECEIVE_TIMEOUT_ERROR;
      }
    } else if (rc != NO_ERROR) {
      // terminal error mid-collective: also drop outstanding postings
      revoke_call_postings(c);
    }
    return rc;
  }

  // Revoke the addresses THIS call posted and never saw complete, so a
  // late write cannot land in memory the caller is about to reuse. A
  // write that landed at the deadline edge (between the failing poll and
  // this revocation) already consumed the posting: purge its completion
  // too, or a future recv posting the same (src, vaddr, bytes, tag)
  // would be falsely satisfied by stale data.
  void revoke_call_postings(Call &c) {
    std::unique_lock<std::mutex> g(rndzv_mu);
    for (auto &pa : c.cstate->posted) {
      revoke_posted_locked(g, pa.src, pa.vaddr, pa.bytes, pa.tag);
      for (auto it = done_q.begin(); it != done_q.end();) {
        // either-side wildcard, matching the completion seekers: a
        // TAG_ANY write completing at the deadline edge of a tagged
        // posting must be purged too, or a future recv reusing the
        // buffer would be falsely satisfied by stale data
        if (it->src == pa.src && it->vaddr == pa.vaddr &&
            it->bytes == pa.bytes &&
            (pa.tag == TAG_ANY || it->tag == TAG_ANY ||
             it->tag == pa.tag))
          it = done_q.erase(it);
        else
          ++it;
      }
    }
    c.cstate->posted.clear();
  }

  uint32_t execute_guts(Call &c) {
    const CommView &cm = c.comm;
    constexpr uint32_t ETH_COMPRESSED = 8;
    uint32_t comp_flags = c.desc[7];
    if ((comp_flags & ETH_COMPRESSED) && c.dtype == ACCL_DT_FLOAT32) {
      uint32_t scenario = c.desc[0];
      uint64_t count = c.desc[1];
      uint64_t in_elems = count, out_elems = count;
      switch (scenario) {
        case SC_SCATTER: in_elems = count * cm.world; break;
        case SC_REDUCE_SCATTER: in_elems = count * cm.world; break;
        case SC_ALLTOALL: in_elems = count * cm.world; out_elems = count * cm.world; break;
        case SC_GATHER: out_elems = count * cm.world; break;
        case SC_ALLGATHER: out_elems = count * cm.world; break;
        default: break;
      }
      // The wire dtype comes from the descriptor's arithconfig row (word
      // 6; exchmem layout arithconfig.py: [unc_bytes, cmp_bytes,
      // ratio_log, compressor, decompressor, is_compressed, lanes...]):
      // compressor 2 = fp32->bf16 (TPU-native extension row), anything
      // else the default fp16 pair — the dtype-pair-generic contract of
      // the reference arithconfig (arithconfig.hpp:102-119).
      if (c.cstate->wire_bf16 < 0) {
        // snapshot on first pass, like the protocol/tuning snapshot: a
        // row rewrite between requeue passes must not flip the wire
        // dtype of a partially-executed call
        uint32_t arcfg_addr = c.desc[6];
        // compressor lanes > 3 are the blockwise-quantized wire
        // (arithconfig.py lanes 4/5: int8 codes + per-block scales);
        // this data plane has no quantized kernel — degrading to a
        // cast would silently put 2 B/elem on a wire the caller sized
        // at ~1 B, so the call is rejected, not reinterpreted
        if (arcfg_addr != 0 && arcfg_addr + 16 < EXCHMEM_BYTES &&
            rd(arcfg_addr + 4 * 3) > 3)
          return COMPRESSION_ERROR;
        c.cstate->wire_bf16 =
            (arcfg_addr != 0 && arcfg_addr + 16 < EXCHMEM_BYTES &&
             rd(arcfg_addr + 4 * 3) == 2)
                ? 1
                : 0;
      }
      bool bf16_wire = c.cstate->wire_bf16 == 1;
      uint16_t (*cast_to)(float) = bf16_wire ? float_to_bf16 : float_to_half;
      float (*cast_from)(uint16_t) =
          bf16_wire ? bf16_to_float : half_to_float;
      auto to_h = [&](const float *src, std::vector<uint16_t> &dst,
                      uint64_t n) {
        dst.resize(n);
        for (uint64_t i = 0; i < n; i++) dst[i] = cast_to(src[i]);
      };
      if (c.op0 && !c.c16_op0) {
        c.c16_op0 = std::make_shared<std::vector<uint16_t>>();
        to_h((const float *)c.op0, *c.c16_op0, in_elems);
      }
      if (c.op1 && !c.c16_op1) {
        c.c16_op1 = std::make_shared<std::vector<uint16_t>>();
        to_h((const float *)c.op1, *c.c16_op1, in_elems);
      }
      if (c.res && !c.c16_res) {
        c.c16_res = std::make_shared<std::vector<uint16_t>>(
            std::max(in_elems, out_elems));
      }
      Call inner = c;  // shares the scratch shared_ptrs
      inner.dtype = bf16_wire ? ACCL_DT_BFLOAT16 : ACCL_DT_FLOAT16;
      inner.desc[7] = comp_flags & ~ETH_COMPRESSED;
      if (c.c16_op0) inner.op0 = c.c16_op0->data();
      if (c.c16_op1) inner.op1 = c.c16_op1->data();
      if (c.c16_res) inner.res = c.c16_res->data();
      uint32_t rc = execute_inner(inner, cm);
      // preserve ALL resumption state (current_step AND the armed
      // deadline) across NOT_READY requeues
      c.current_step = inner.current_step;
      c.deadline = inner.deadline;
      c.deadline_set = inner.deadline_set;
      if (rc == NOT_READY) return NOT_READY;
      // only ranks that own the output write it back: gather/reduce
      // deliver to root alone (non-root recvbufs stay untouched, matching
      // the uncompressed path)
      uint32_t root = c.desc[3];
      bool owns_res =
          !(scenario == SC_GATHER || scenario == SC_REDUCE) || root == cm.rank;
      if (c.res && rc == NO_ERROR && owns_res) {
        float *dst = (float *)c.res;
        for (uint64_t i = 0; i < out_elems; i++)
          dst[i] = cast_from((*c.c16_res)[i]);
      }
      // bcast mutates op0 on receivers only: compression is wire-only, so
      // the root's full-precision source stays untouched (reference
      // semantics)
      if (scenario == SC_BCAST && c.op0 && rc == NO_ERROR && root != cm.rank) {
        float *dst = (float *)c.op0;
        for (uint64_t i = 0; i < in_elems; i++)
          dst[i] = cast_from((*c.c16_op0)[i]);
      }
      return rc;
    }
    return execute_inner(c, cm);
  }

  uint32_t execute_inner(Call &c, const CommView &cm) {
    uint32_t scenario = c.desc[0];
    uint64_t count = c.desc[1];
    uint32_t root = c.desc[3];
    uint32_t func = c.desc[4];
    uint32_t tag = c.desc[5];
    uint64_t eb = dtype_bytes(c.dtype);
    uint64_t bytes = count * eb;
    auto *op0 = (const uint8_t *)c.op0;
    auto *op1 = (const uint8_t *)c.op1;
    auto *res = (uint8_t *)c.res;
    // rooted collectives: the root is communicator-relative and must
    // exist, or the group hangs waiting on a root nobody is
    switch (scenario) {
      case SC_BCAST: case SC_SCATTER: case SC_GATHER: case SC_REDUCE:
        if (root >= cm.world) return DMA_DECODE_ERROR;
        break;
      default:
        break;
    }
    switch (scenario) {
      case SC_NOP:
        return NO_ERROR;
      case SC_CONFIG:
        switch (func) {
          case 2: timeout_ms = count; return NO_ERROR;      // set_timeout
          case 3: max_eager = (uint32_t)count; return NO_ERROR;
          case 4: max_rndzv = count; return NO_ERROR;
          default: return NO_ERROR;  // reset/enable_pkt are no-ops here
        }
      case SC_COPY:
        std::memcpy(res, op0, bytes);
        return NO_ERROR;
      case SC_COMBINE: {
        std::memcpy(res, op0, bytes);
        return combine_buffers(c.dtype, func, res, op1, count);
      }
      default:
        break;
    }
    // Everything below is a resumable op sequence over the call's state
    // machine (the firmware retry contract for every scenario,
    // ccl_offload_control.c:2308-2483).
    Ops o{*this, c, *c.cstate, tag};
    switch (scenario) {
      case SC_SEND:
        // root_src_dst is the destination rank, communicator-relative
        // (reference send semantics)
        if (root >= cm.world) return DMA_DECODE_ERROR;
        return o.send(cm.g(root), op0, bytes);
      case SC_RECV:
        // root_src_dst is the source rank. Non-strict tag matching: a
        // head-tag mismatch stays NOT_READY because another parked recv
        // may legally consume the head segment first.
        if (root >= cm.world) return DMA_DECODE_ERROR;
        return o.recv(cm.g(root), res, bytes, /*strict=*/false);
      case SC_BCAST:
        return do_bcast(o, cm, (uint8_t *)op0, bytes, root);
      case SC_SCATTER:
        return do_scatter(o, cm, op0, res, bytes, root);
      case SC_GATHER:
        return do_gather(o, cm, op0, res, bytes, root);
      case SC_ALLGATHER:
        return do_allgather(o, cm, op0, res, bytes);
      case SC_REDUCE:
        return do_reduce(o, cm, c.dtype, func, op0, res, count, root);
      case SC_ALLREDUCE:
        return do_allreduce(o, cm, c.dtype, func, op0, res, count);
      case SC_REDUCE_SCATTER:
        return do_reduce_scatter(o, cm, c.dtype, func, op0, res, count);
      case SC_ALLTOALL:
        return do_alltoall(o, cm, op0, res, bytes);
      case SC_BARRIER:
        return do_barrier(o, cm);
      default:
        return COLLECTIVE_NOT_IMPLEMENTED;
    }
  }

  // Collectives serialize per communicator (see inflight_comms); p2p and
  // local scenarios have call identity (tags / no wire) and stay freely
  // concurrent — the round-2 parked-recv semantics.
  static bool comm_serialized(uint32_t scenario) {
    switch (scenario) {
      case SC_BCAST: case SC_SCATTER: case SC_GATHER: case SC_REDUCE:
      case SC_ALLGATHER: case SC_ALLREDUCE: case SC_REDUCE_SCATTER:
      case SC_BARRIER: case SC_ALLTOALL:
        return true;
      default:
        return false;
    }
  }

  void sequencer() {
    while (!stop.load()) {
      Call c;
      {
        std::unique_lock<std::mutex> lk(call_mu);
        auto pick = [&]() -> bool {
          // prefer fresh calls (run() order), skipping collectives whose
          // communicator already has one in flight; then parked retries
          for (auto it = call_q.begin(); it != call_q.end(); ++it) {
            if (comm_serialized(it->desc[0])) {
              auto f = inflight_comms.find(it->desc[2]);
              if (f != inflight_comms.end() && f->second > 0) continue;
            }
            c = std::move(*it);
            call_q.erase(it);
            return true;
          }
          if (!retry_q.empty()) {
            c = std::move(retry_q.front());
            retry_q.pop_front();
            return true;
          }
          return false;
        };
        call_cv.wait(lk, [&] { return stop.load() || pick(); });
        if (stop.load()) return;
        if (!c.started) {
          c.started = true;
          if (comm_serialized(c.desc[0])) inflight_comms[c.desc[2]]++;
        }
      }
      if (debug_on && c.desc[0] != SC_RECV)
        fprintf(stderr, "[r%u] exec scenario=%u count=%u\n", rank, c.desc[0], c.desc[1]);
      // ACCL_RT_FAULT_KILL_RANK countdown: after N completed data-plane
      // calls the rank wedges permanently (config/nop are host plumbing
      // and never count — the soak kills mid data stream)
      if (kill_after_calls >= 0 && !killed.load(std::memory_order_relaxed) &&
          c.desc[0] != SC_CONFIG && c.desc[0] != SC_NOP && !c.started_counted) {
        c.started_counted = true;
        if (kill_after_calls == 0)
          wedge();
        else
          kill_after_calls--;
      }
      uint64_t ev0 = rx_events.load(std::memory_order_acquire);
      stat_passes++;
      uint32_t rc = execute(c);
      if (debug_on && c.desc[0] != SC_RECV)
        fprintf(stderr, "[r%u] done scenario=%u rc=%u\n", rank, c.desc[0], rc);
      if (rc == NOT_READY) {
        {
          std::lock_guard<std::mutex> lk(call_mu);
          retry_q.push_back(std::move(c));
        }
        // park until a NEW rx event (progress needs a segment/address/
        // completion, not a re-poll) — but only if none arrived since
        // this pass started, or the arrival gap costs a full timeout
        std::unique_lock<std::mutex> lk(rx_mu);
        if (rx_events.load(std::memory_order_acquire) == ev0) {
          stat_parks++;
          auto t0 = std::chrono::steady_clock::now();
          // The event-counter predicate makes this wait race-free (any
          // rx progress notifies rx_cv and bumps rx_events), so the cap
          // is a pure lost-wakeup backstop. 200 us proved far too eager
          // on single-core CI hosts: with P sequencers parked, 5k
          // spurious wakeups/s stole the core from the threads moving
          // data (rt_stats parks ~= seek_miss signature); 2 ms keeps
          // the backstop while the predicate does the real waking.
          cv_wait_for(rx_cv, lk, std::chrono::milliseconds(2), [&] {
            return stop.load() ||
                   rx_events.load(std::memory_order_acquire) != ev0;
          });
          stat_park_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
                              std::chrono::steady_clock::now() - t0)
                              .count();
        }
        continue;
      }
      // terminal (success OR error): any stream ownership this call holds
      // must not outlive it — its CollState is about to be destroyed
      if (c.cstate) release_rx_ownership(c.cstate.get());
      if (trace_on) record_span(c, rc);
      auto dur = std::chrono::steady_clock::now() - c.t_start;
      if (comm_serialized(c.desc[0])) {
        // release the communicator's serialization slot: a deferred
        // same-comm call becomes runnable on the next pick()
        std::lock_guard<std::mutex> lk(call_mu);
        auto f = inflight_comms.find(c.desc[2]);
        if (f != inflight_comms.end() && --f->second == 0)
          inflight_comms.erase(f);
      }
      {
        std::lock_guard<std::mutex> lk(comp_mu);
        auto &comp = completions[c.handle];
        comp->retcode = rc;
        comp->duration_ns =
            std::chrono::duration_cast<std::chrono::nanoseconds>(dur).count();
        comp->done.store(1);
      }
      comp_cv.notify_all();
      wr(RETCODE, rc);
    }
  }
};

// glibc's std::mutex is zero-initialized — no pthread_mutex_init call —
// so ThreadSanitizer never observes a mutex's construction. If the heap
// block previously hosted a pthread mutex that WAS destroyed (the Python
// host destroys them constantly), the stale "destroyed" sync state
// suppresses lock-based happens-before and every guarded access pair
// reports as a false race. Announce each runtime mutex's birth.
#if defined(__SANITIZE_THREAD__)
extern "C" void __tsan_mutex_create(void *addr, unsigned flags);
static void tsan_announce_mutexes(accl_rt *rt) {
  for (std::mutex *m :
       {&rt->exch_mu, &rt->hello_mu, &rt->rx_mu, &rt->rndzv_mu, &rt->call_mu,
        &rt->comp_mu, &rt->trace_mu, &rt->fault_mu, &rt->rely_mu,
        &rt->rng_mu})
    __tsan_mutex_create(m, 0);
}
#else
static void tsan_announce_mutexes(accl_rt *) {}
#endif

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

accl_rt_t *accl_rt_create_ex(uint32_t world, uint32_t rank,
                             const uint16_t *ports, uint32_t n_rx_bufs,
                             uint32_t rx_buf_bytes, uint32_t max_eager_bytes,
                             uint64_t max_rndzv_bytes, uint32_t transport) {
  auto *rt = new accl_rt();
  tsan_announce_mutexes(rt);
  rt->world = world;
  rt->rank = rank;
  rt->rx_buf_bytes = rx_buf_bytes;
  rt->max_eager = max_eager_bytes;
  rt->max_rndzv = max_rndzv_bytes;
  rt->rx_slots.resize(n_rx_bufs);
  rt->base_rx_slots = n_rx_bufs;
  for (size_t i = 0; i < rt->rx_slots.size(); i++) rt->idle_q.push_back(i);
  rt->wr(IDCODE, 0xACC17B00u);
  if (const char *s = getenv("ACCL_RT_SHAPE")) {
    if (!strcmp(s, "ring")) rt->shape_override = 1;
    else if (!strcmp(s, "logp")) rt->shape_override = 2;
  }
  if (const char *s = getenv("ACCL_RT_FAULT_DELAY_TAIL_MS"))
    rt->fault_delay_tail_ms = atoi(s);
  if (const char *s = getenv("ACCL_RT_FAULT_DROP_TAIL"))
    rt->fault_drop_tail = atoi(s) != 0;
  if (const char *s = getenv("ACCL_RT_FAULT_KILL_RANK")) {
    if ((uint32_t)atoi(s) == rank) {
      rt->kill_after_calls = 0;
      if (const char *a = getenv("ACCL_RT_FAULT_KILL_AFTER"))
        rt->kill_after_calls = atoi(a) < 0 ? 0 : atoi(a);
    }
  }
  if (const char *s = getenv("ACCL_RT_WAN_ALPHA_US"))
    rt->wan_alpha_us = (uint32_t)atoi(s);
  if (const char *s = getenv("ACCL_RT_WAN_GBPS")) {
    double g = atof(s);
    if (g > 0) rt->wan_bytes_per_us = g * 1000.0;  // 1 GB/s = 1000 B/us
  }
  if (const char *s = getenv("ACCL_RT_TRACE"))
    rt->trace_on = atoi(s) != 0;
  if (const char *s = getenv("ACCL_RT_TRACE_CAP")) {
    long cap = atol(s);
    if (cap > 0) rt->trace_cap = (size_t)cap;
  }
  // reliability sublayer + seeded chaos fault model (see the struct's
  // rely block). ACCL_RT_RELY is world-uniform by contract.
  rt->debug_on = getenv("ACCL_RT_DEBUG") != nullptr;
  if (const char *s = getenv("ACCL_RT_RELY")) rt->rely_on = atoi(s) != 0;
  if (const char *s = getenv("ACCL_RT_RELY_NACK_MAX")) {
    int v = atoi(s);
    if (v > 0) rt->nack_max = (uint32_t)v;
  }
  if (const char *s = getenv("ACCL_RT_RELY_BUF_BYTES")) {
    long long v = atoll(s);
    if (v > 0) rt->retx_budget_bytes = (uint64_t)v;
  }
  {
    auto pct = [](const char *name) {
      const char *s = getenv(name);
      double v = s ? atof(s) : 0.0;
      return v > 0 ? v : 0.0;
    };
    rt->fault_loss_pct = pct("ACCL_RT_FAULT_LOSS_PCT");
    rt->fault_corrupt_pct = pct("ACCL_RT_FAULT_CORRUPT_PCT");
    rt->fault_dup_pct = pct("ACCL_RT_FAULT_DUP_PCT");
    rt->fault_reorder_pct = pct("ACCL_RT_FAULT_REORDER_PCT");
    rt->fault_pct_armed = rt->fault_loss_pct + rt->fault_corrupt_pct +
                              rt->fault_dup_pct + rt->fault_reorder_pct >
                          0;
    uint64_t seed = 1;
    if (const char *s = getenv("ACCL_RT_FAULT_SEED"))
      seed = strtoull(s, nullptr, 10);
    // distinct deterministic stream per (seed, rank)
    rt->rng_state =
        (seed + 0x9E3779B97F4A7C15ull) * (rank + 0x632BE59BD9B4E019ull);
  }
  rt->rely_wire = rt->rely_on &&
                  (transport != ACCL_RT_TRANSPORT_LOCAL ||
                   rt->fault_pct_armed);
  // ----- wire shape: legacy cost model, lanes, TX batching ----------------
  // ACCL_RT_WIRE_LEGACY=1: pre-vectored transmit (per-frame syscalls,
  // coalescing copies) — the A/B baseline `bench --wire-gate` measures
  // the scatter-gather path against. Legacy implies the single-lane
  // bit-identical wire.
  if (const char *s = getenv("ACCL_RT_WIRE_LEGACY"))
    rt->legacy_wire = atoi(s) != 0;
  // ACCL_RT_LANES (session transport only): per-peer lanes. Lane 0
  // carries small messages and all control traffic; lane 1 carries bulk
  // messages >= ACCL_RT_LANE_BULK_BYTES, so a jumbo frame in flight
  // cannot head-of-line-block a small message. Default 1 = the legacy
  // single-stream wire, bit-identical framing.
  if (transport == ACCL_RT_TRANSPORT_TCP && !rt->legacy_wire) {
    if (const char *s = getenv("ACCL_RT_LANES")) {
      int v = atoi(s);
      if (v > (int)WIRE_MAX_LANES) v = WIRE_MAX_LANES;
      if (v >= 1) rt->n_lanes = (uint32_t)v;
    }
  }
  if (const char *s = getenv("ACCL_RT_LANE_BULK_BYTES")) {
    long long v = atoll(s);
    if (v > 0) rt->lane_bulk_bytes = (uint64_t)v;
  }
  // TX batching (many frames -> one vectored syscall) stays OFF where
  // per-frame emission is part of the contract: the legacy cost model,
  // the seeded chaos stream (frame-order determinism), the WAN shaper
  // (per-frame charges), the in-process POE (delivery IS the call), and
  // the one-shot tail levers (their wire-order asserts reason about
  // single frames).
  rt->tx_batch_on = !rt->legacy_wire && !rt->fault_pct_armed &&
                    transport != ACCL_RT_TRANSPORT_LOCAL &&
                    rt->wan_alpha_us == 0 && rt->wan_bytes_per_us <= 0 &&
                    rt->fault_delay_tail_ms == 0 && !rt->fault_drop_tail;
  // per-stream state: one seqn stream per (peer, lane) sid
  const uint32_t n_streams = world * rt->n_lanes;
  rt->inbound_seq.assign(n_streams, 0);
  rt->outbound_seq.assign(n_streams, 0);
  rt->src_valid_count.assign(n_streams, 0);
  rt->retx.resize(n_streams);
  rt->want.assign(n_streams, WantState{});
  rt->acked_upto.assign(n_streams, 0);
  rt->last_ack_t.assign(n_streams, std::chrono::steady_clock::now());
  auto start_rely = [](accl_rt *r) {
    if (r->rely_wire) r->rely_thread = std::thread([r] { r->rely_loop(); });
  };
  acclw::PoeConfig pc;
  pc.world = world;
  pc.rank = rank;
  pc.ports = ports;
  pc.lanes = rt->n_lanes;
  pc.legacy_wire = rt->legacy_wire;
  pc.debug = rt->debug_on;
  if ((rt->wan_alpha_us > 0 || rt->wan_bytes_per_us > 0) &&
      transport != ACCL_RT_TRANSPORT_LOCAL)
    pc.shaper = [rt](size_t payload_len) { rt->wan_charge(payload_len); };

  if (transport == ACCL_RT_TRANSPORT_LOCAL) {
    // intra-process POE: no sockets, no rx threads — the sender's
    // thread delivers straight into the peer runtime's sink.
    // Bring-up IS the registry: send_frames waits for a peer's entry.
    rt->local_mode = true;
    rt->poe = acclw::make_local_poe(pc);
    if (!rt->poe->connect(rt)) {
      delete rt;  // port collision: refuse rather than misroute
      return nullptr;
    }
    rt->seq_thread = std::thread([rt] { rt->sequencer(); });
    start_rely(rt);
    return rt;
  }

  if (transport == ACCL_RT_TRANSPORT_UDP) {
    // sessionless datagram POE: one SOCK_DGRAM socket, no connections.
    // Segment must fit one datagram with its header.
    if (rt->rx_buf_bytes > 60000) rt->rx_buf_bytes = 60000;
    rt->udp_mode = true;
    // hello state must exist BEFORE connect: the rx thread it spawns
    // can deliver a peer's hello immediately
    rt->hello_seen.assign(world, false);
    rt->hello_seen[rank] = true;
    rt->poe = acclw::make_udp_poe(pc);
    if (!rt->poe->connect(rt)) {
      delete rt;  // bind failure
      return nullptr;
    }
    // bring-up handshake: solicit hellos until every peer answered
    // (datagrams sent before a peer binds are simply lost, so re-solicit)
    auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(30);
    for (;;) {
      std::vector<uint32_t> missing;
      {
        std::lock_guard<std::mutex> g(rt->hello_mu);
        for (uint32_t i = 0; i < world; i++)
          if (!rt->hello_seen[i]) missing.push_back(i);
      }
      if (missing.empty()) break;
      if (std::chrono::steady_clock::now() > deadline) {
        accl_rt_destroy(rt);
        return nullptr;
      }
      for (uint32_t i : missing)
        rt->frame_out(i, MSG_HELLO, 0, 0, 0, 0, nullptr, 0);
      std::unique_lock<std::mutex> lk(rt->hello_mu);
      cv_wait_for(rt->hello_cv, lk, std::chrono::milliseconds(50));
    }
    rt->seq_thread = std::thread([rt] { rt->sequencer(); });
    start_rely(rt);
    return rt;
  }

  // session POE: full TCP mesh, one ordered stream per (peer, lane)
  rt->poe = acclw::make_tcp_poe(pc);
  if (!rt->poe->connect(rt)) {
    accl_rt_destroy(rt);
    return nullptr;
  }
  rt->seq_thread = std::thread([rt] { rt->sequencer(); });
  start_rely(rt);
  return rt;
}

accl_rt_t *accl_rt_create(uint32_t world, uint32_t rank,
                          const uint16_t *ports, uint32_t n_rx_bufs,
                          uint32_t rx_buf_bytes, uint32_t max_eager_bytes,
                          uint64_t max_rndzv_bytes) {
  return accl_rt_create_ex(world, rank, ports, n_rx_bufs, rx_buf_bytes,
                           max_eager_bytes, max_rndzv_bytes,
                           ACCL_RT_TRANSPORT_TCP);
}

void accl_rt_destroy(accl_rt_t *rt) {
  rt->stop.store(true);
  rt->call_cv.notify_all();
  rt->rx_cv.notify_all();
  rt->rndzv_cv.notify_all();
  rt->hello_cv.notify_all();
  // tear the wire down first: begin_shutdown revokes the sockets and
  // unblocks the POE's rx loops (shutdown()/self-poke/registry
  // deregistration) — senders see the revoked fds and fail fast
  if (rt->poe) rt->poe->begin_shutdown();
  // reap the runtime's own sender threads BEFORE Poe::join closes the
  // revoked fds: the rely/sequencer threads may still be inside a
  // send syscall on an fd they loaded before revocation, and closing
  // under them would hand the descriptor number to a concurrent open
  if (rt->seq_thread.joinable()) rt->seq_thread.join();
  if (rt->rely_thread.joinable()) rt->rely_thread.join();
  {
    std::lock_guard<std::mutex> g(rt->fault_mu);
    for (auto &t : rt->fault_threads)
      if (t.joinable()) t.join();
  }
  // now reap the rx loops and close the deferred fds — after this no
  // sink call is in flight
  if (rt->poe) rt->poe->join();
  if (getenv("ACCL_RT_STATS"))
    fprintf(stderr,
            "[r%u] stats: passes=%llu parks=%llu park_ms=%.1f "
            "seek_hit=%llu seek_miss=%llu\n",
            rt->rank, (unsigned long long)rt->stat_passes.load(),
            (unsigned long long)rt->stat_parks.load(),
            rt->stat_park_ns.load() / 1e6,
            (unsigned long long)rt->stat_seek_hit.load(),
            (unsigned long long)rt->stat_seek_miss.load());
  delete rt;
}

int64_t accl_rt_start(accl_rt_t *rt, const uint32_t desc[15],
                      uint32_t data_type, void *op0, void *op1, void *res) {
  Call c;
  std::memcpy(c.desc, desc, 15 * 4);
  c.dtype = data_type;
  c.op0 = op0;
  c.op1 = op1;
  c.res = res;
  c.t_start = std::chrono::steady_clock::now();
  if (rt->trace_on) {
    // counter-snapshot base for the span's per-call deltas (global
    // sequencer activity over this call's lifetime)
    c.ctr0[0] = rt->stat_passes.load();
    c.ctr0[1] = rt->stat_parks.load();
    c.ctr0[2] = rt->stat_seek_hit.load();
    c.ctr0[3] = rt->stat_seek_miss.load();
  }
  int64_t h;
  {
    std::lock_guard<std::mutex> lk(rt->comp_mu);
    h = rt->next_handle++;
    rt->completions[h] = std::make_shared<Completion>();
  }
  c.handle = h;
  {
    std::lock_guard<std::mutex> lk(rt->call_mu);
    rt->call_q.push_back(std::move(c));
  }
  rt->call_cv.notify_all();
  return h;
}

int accl_rt_test(accl_rt_t *rt, int64_t handle) {
  std::lock_guard<std::mutex> lk(rt->comp_mu);
  auto it = rt->completions.find(handle);
  return it != rt->completions.end() && it->second->done.load();
}

int accl_rt_wait(accl_rt_t *rt, int64_t handle, uint64_t timeout_ms) {
  std::unique_lock<std::mutex> lk(rt->comp_mu);
  auto it = rt->completions.find(handle);
  if (it == rt->completions.end()) return 0;
  auto comp = it->second;
  auto pred = [&] { return comp->done.load() != 0; };
  if (timeout_ms == 0) {
    rt->comp_cv.wait(lk, pred);
    return 1;
  }
  return cv_wait_for(rt->comp_cv, lk, std::chrono::milliseconds(timeout_ms),
                     pred)
             ? 1
             : 0;
}

uint32_t accl_rt_retcode(accl_rt_t *rt, int64_t handle) {
  std::lock_guard<std::mutex> lk(rt->comp_mu);
  auto it = rt->completions.find(handle);
  return it == rt->completions.end() ? 0 : it->second->retcode;
}

uint64_t accl_rt_duration_ns(accl_rt_t *rt, int64_t handle) {
  std::lock_guard<std::mutex> lk(rt->comp_mu);
  auto it = rt->completions.find(handle);
  return it == rt->completions.end() ? 0 : it->second->duration_ns;
}

/* Drop a completed call's bookkeeping (call after reading retcode and
 * duration) so long-lived ranks do not accumulate completion records. */
void accl_rt_release(accl_rt_t *rt, int64_t handle) {
  std::lock_guard<std::mutex> lk(rt->comp_mu);
  rt->completions.erase(handle);
}

uint32_t accl_rt_read(accl_rt_t *rt, uint32_t addr) { return rt->rd(addr); }

// Permanently wedge a rank (see the ACCL_RT_FAULT_KILL_RANK lever): the
// programmatic kill the fault-gate soak fires mid-stream. Idempotent.
void accl_rt_kill(accl_rt_t *rt) { rt->wedge(); }

// Reconfiguration fence (see accl_rt::flush_rx): drain stale frames of
// the old membership's aborted collectives before the recovery
// communicator's first call. Quiescent caller contract.
void accl_rt_flush_rx(accl_rt_t *rt) { rt->flush_rx(); }

// Cumulative sequencer counters (execute passes, event-counter parks,
// nanoseconds parked, rx-seek hits/misses): the always-on form of the
// ACCL_RT_STATS destroy-time dump, so callers can profile phases of a
// live run — the observability sibling of the per-call PERFCNT word.
void accl_rt_get_stats(accl_rt_t *rt, uint64_t out[5]) {
  out[0] = rt->stat_passes.load();
  out[1] = rt->stat_parks.load();
  out[2] = rt->stat_park_ns.load();
  out[3] = rt->stat_seek_hit.load();
  out[4] = rt->stat_seek_miss.load();
}

// Versioned counter surface (acclrt.h ACCL_RT_STAT2_*): the old 5-word
// accl_rt_get_stats stays ABI-stable above; this one carries the wire-
// health counters too and returns the total count available, so a
// caller built against an older header reads the prefix it knows.
size_t accl_rt_get_stats2(accl_rt_t *rt, uint64_t *out, size_t cap) {
  const uint64_t vals[ACCL_RT_STATS2_COUNT] = {
      rt->stat_passes.load(),      rt->stat_parks.load(),
      rt->stat_park_ns.load(),     rt->stat_seek_hit.load(),
      rt->stat_seek_miss.load(),   rt->stat_tx_frames.load(),
      rt->stat_rx_frames.load(),   rt->stat_crc_drops.load(),
      rt->stat_dup_drops.load(),   rt->stat_retx_sent.load(),
      rt->stat_retx_miss.load(),   rt->stat_nack_sent.load(),
      rt->stat_nack_rx.load(),     rt->stat_ack_sent.load(),
      rt->stat_ack_rx.load(),      rt->stat_rndzv_drops.load(),
      rt->stat_inj_loss.load(),    rt->stat_inj_corrupt.load(),
      rt->stat_inj_dup.load(),     rt->stat_inj_reorder.load(),
      rt->stat_rely_ns.load(),
      rt->poe ? rt->poe->tx_syscalls() : 0,
      rt->poe ? rt->poe->tx_batched() : 0,
  };
  size_t n = cap < ACCL_RT_STATS2_COUNT ? cap : (size_t)ACCL_RT_STATS2_COUNT;
  for (size_t i = 0; i < n; i++) out[i] = vals[i];
  return ACCL_RT_STATS2_COUNT;
}

void accl_rt_write(accl_rt_t *rt, uint32_t addr, uint32_t value) {
  rt->wr(addr, value);
}

// Drain the device-resident trace ring, oldest first (see acclrt.h).
size_t accl_rt_trace_read(accl_rt_t *rt, accl_rt_span_t *out, size_t cap,
                          uint64_t *dropped) {
  std::lock_guard<std::mutex> g(rt->trace_mu);
  if (dropped) *dropped = rt->trace_dropped;
  size_t n = 0;
  while (n < cap && !rt->trace_q.empty()) {
    out[n++] = rt->trace_q.front();
    rt->trace_q.pop_front();
  }
  return n;
}

// Snapshot of the eager rx ring (the reference's dump_eager_rx_buffers,
// accl.cpp:964-1012: one line per spare-buffer descriptor with status and
// the last-landed header fields). Writes a NUL-terminated report into out
// (truncated at cap); returns the untruncated length a la snprintf.
size_t accl_rt_dump_rxbufs(accl_rt_t *rt, char *out, size_t cap) {
  std::string s;
  {
    std::lock_guard<std::mutex> g(rt->rx_mu);
    s += "eager rx ring: " + std::to_string(rt->rx_slots.size()) +
         " slots (configured " + std::to_string(rt->base_rx_slots) +
         "), " + std::to_string(rt->idle_q.size()) + " idle\n";
    for (size_t i = 0; i < rt->rx_slots.size(); i++) {
      const RxSlot &sl = rt->rx_slots[i];
      s += "slot " + std::to_string(i) + ": " +
           (sl.status == RxSlot::VALID ? "VALID" : "IDLE");
      if (sl.status == RxSlot::VALID)
        s += " src " + std::to_string(sl.src) + " tag " +
             std::to_string(sl.tag) + " seqn " + std::to_string(sl.seqn) +
             " len " + std::to_string(sl.data.size());
      s += "\n";
    }
  }
  if (cap) {
    size_t n = std::min(cap - 1, s.size());
    std::memcpy(out, s.data(), n);
    out[n] = '\0';
  }
  return s.size();
}

}  // extern "C"
