// accl-tpu native runtime: the POE seam. One small vtable every
// Protocol Offload Engine implements — connect / send_frames / (rx
// loops feeding a sink) / stats — with three engines behind it:
//
//   TcpPoe    session full mesh, one ordered byte stream per
//             (peer, lane); scatter-gather writev transmit, many frames
//             per syscall (the EasyNet-class POE)
//   UdpPoe    one shared datagram socket, every frame a standalone
//             packet; sendmmsg batching (the VNX-UDP POE analog)
//   LocalPoe  intra-process registry, frames delivered by direct call
//
// The seam carries ALREADY-BUILT frames only: the transport never
// computes a CRC, never retains a frame for retransmit, never looks at
// seqn streams — that is all session/reliability policy above the seam
// (transport.cpp must not include reliability.h; `make seamcheck`).

#ifndef ACCLRT_TRANSPORT_H
#define ACCLRT_TRANSPORT_H

#include <cstddef>
#include <cstdint>
#include <functional>
#include <memory>
#include <sys/types.h>

#include "wire.h"

namespace acclw {

// Incremental access to one inbound frame's payload bytes. Datagram /
// in-process POEs hand the whole payload resident (data() non-null);
// the stream POE exposes the socket so the session can land bytes
// DIRECTLY at their destination (the zero-copy eager/rendezvous
// landings) with poll-bounded reads (poll_in + read_avail — the pin
// re-check between slices is the revocation protocol's liveness bound).
class PayloadSource {
 public:
  virtual ~PayloadSource() = default;
  // whole payload resident in memory (spans remaining() bytes from the
  // CURRENT read position); nullptr for stream sources
  virtual const uint8_t *data() const { return nullptr; }
  virtual size_t remaining() const = 0;
  // read exactly n bytes; false = link dead / shutdown
  virtual bool read_exact(void *dst, size_t n) = 0;
  // wait up to timeout_ms for readability: >0 ready, 0 timeout, <0 error
  // (mem-backed sources are always ready)
  virtual int poll_in(int timeout_ms) = 0;
  // single bounded read of up to n bytes (no waiting beyond one recv);
  // >0 bytes consumed, <=0 link dead
  virtual ssize_t read_avail(void *dst, size_t n) = 0;
};

// The session side of the seam: one call per inbound frame, invoked on
// the POE's rx thread (or the sender's thread, for the in-process POE).
// The sink must consume the payload via `body`; any unconsumed
// remainder is drained by the stream POE to preserve framing. Returning
// false tears the link down (fatal decode error / shutdown).
class PoeSink {
 public:
  virtual ~PoeSink() = default;
  virtual bool on_frame(uint32_t lane, const MsgHeader &h,
                        PayloadSource &body) = 0;
};

struct PoeConfig {
  uint32_t world = 0;
  uint32_t rank = 0;
  const uint16_t *ports = nullptr;  // per-rank port map (127.0.0.1)
  uint32_t lanes = 1;               // per-peer lanes (TCP only, <= WIRE_MAX_LANES)
  // ACCL_RT_WIRE_LEGACY=1: the pre-vectored cost model — per-frame
  // syscalls, payload coalescing copies, no batching. Kept as the A/B
  // baseline `bench --wire-gate` measures the vectored path against.
  bool legacy_wire = false;
  // Optional per-frame WAN charge (the emulated slow-tier shaper): when
  // set, the POE charges it per frame under the same per-(dst, lane)
  // serialization the wire itself has. Never set for the local POE.
  std::function<void(size_t payload_len)> shaper;
  bool debug = false;  // gate bring-up/teardown stderr prints
};

class Poe {
 public:
  virtual ~Poe() = default;
  // Blocking bring-up (mesh handshake / datagram bind / registry
  // registration) and rx-thread spawn; frames flow into `sink` from the
  // moment this returns true. False = bring-up failure (caller owns
  // cleanup via destructor).
  virtual bool connect(PoeSink *sink) = 0;
  // Ship n frames to (dst, lane), in order, scatter-gather. The views'
  // payload pointers must stay valid for the duration of the call (the
  // caller's batch holds FramePtr pins / caller buffers). Returns false
  // when the link is down or shutdown began.
  virtual bool send_frames(uint32_t dst, uint32_t lane, const FrameView *fv,
                           size_t n) = 0;
  // Unblock rx loops and refuse new sends (idempotent)...
  virtual void begin_shutdown() = 0;
  // ...then reap them (destructor does both if the caller didn't).
  virtual void join() = 0;
  virtual uint32_t lanes() const = 0;
  // wire-health counters (accl_rt_get_stats2 TX_SYSCALLS / TX_BATCHED):
  // transmit syscalls issued, and frames that shipped inside a
  // multi-frame batch (the syscalls-per-frame ratio the batching win
  // shows up in).
  virtual uint64_t tx_syscalls() const = 0;
  virtual uint64_t tx_batched() const = 0;
  // Debug accounting for the no-double-copy invariant: payload bytes
  // coalesced into a transmit staging buffer. Stays ZERO on the
  // vectored path (scatter-gather ships borrowed pointers); only the
  // legacy cost model copies. The session asserts this after each send.
  virtual uint64_t payload_copies() const = 0;
};

std::unique_ptr<Poe> make_tcp_poe(const PoeConfig &cfg);
std::unique_ptr<Poe> make_udp_poe(const PoeConfig &cfg);
std::unique_ptr<Poe> make_local_poe(const PoeConfig &cfg);

}  // namespace acclw

#endif  // ACCLRT_TRANSPORT_H
