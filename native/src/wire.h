// accl-tpu native runtime: wire format shared by the transport /
// reliability / session translation units (the one header every side of
// the POE seam may include). Holds ONLY the on-the-wire frame layout and
// the frame container types — no sockets, no retransmit state, no
// session logic.

#ifndef ACCLRT_WIRE_H
#define ACCLRT_WIRE_H

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <memory>
#include <vector>

namespace acclw {

// ---------------------------------------------------------------------------
// Wire format: 64-byte header (eth_intf.h:94-151 analog) + payload
// ---------------------------------------------------------------------------
enum MsgType : uint32_t {
  MSG_EGR_DATA = 0,    // eager segment into an rx slot
  MSG_RNDZV_ADDR = 1,  // receiver -> sender address notification
  MSG_RNDZV_WRITE = 2, // sender -> receiver one-sided write payload
  MSG_HELLO = 3,       // datagram bring-up solicit (reply expected)
  MSG_HELLO_ACK = 4,   // datagram bring-up reply (no further reply)
  // reliability sublayer control frames (header-only; seqn is the
  // REFERENCED data seqn, never a slot in the per-peer seqn stream):
  MSG_ACK = 5,   // receiver -> sender: cumulative "everything below
                 // seqn landed" — sender GCs its retransmit buffer
  MSG_NACK = 6,  // receiver -> sender: "resend (src, seqn)" — the
                 // selective-retransmit request a gap or CRC drop arms
};

struct MsgHeader {
  uint32_t magic;
  uint32_t msg_type;
  uint32_t src;
  uint32_t dst;
  uint32_t tag;
  uint32_t seqn;
  // CRC32C over the whole frame (header with this field zeroed +
  // payload), set on every frame when the reliability sublayer is on
  // (ACCL_RT_RELY, default 1; the field was dead pad before — the
  // offload engine owning integrity below the host, README.md:6). A
  // mismatch is counted and the frame DROPPED, never landed: corrupt
  // data cannot reach a reduce lane; the seqn gap it leaves is
  // repaired by the NACK path like a lost frame.
  uint32_t crc;
  // low 16 bits: the host flag (desc word 8's host<<8 nibble, 0/1 in
  // practice); high 16 bits: the LANE this frame rides (see wire_lane).
  // Lanes are independent per-peer seqn streams — a jumbo eager message
  // on the bulk lane cannot head-of-line-block a small message on the
  // default lane. Rendezvous and bring-up frames always ride lane 0.
  uint32_t host;
  uint64_t bytes;  // payload length / rendezvous size
  uint64_t vaddr;  // rendezvous target address
  // total bytes of the eager MESSAGE this segment belongs to: the
  // receiver-side message boundary. Without it a parked recv whose count
  // mismatches the head message would consume it as partial fill and
  // misassemble two messages into one buffer (the reference wire needs no
  // equivalent because rxbuf_seek pairs whole DMA commands, not byte
  // streams). Rides every MSG_EGR_DATA segment, with msg_off locating the
  // segment inside its message (0 = message head) so an orphaned
  // continuation segment — left behind when a mid-message recv times out —
  // can never masquerade as a fresh head of the same length.
  uint64_t msg_bytes;
  uint64_t msg_off;
};
static_assert(sizeof(MsgHeader) == 64, "ACCL header is 64 bytes");
// Bumped (…02) when the header's pad bytes became msg_bytes/msg_off
// framing, (…03) when the dead strm word became the frame CRC32C and
// MSG_ACK/MSG_NACK joined the protocol, (…04) when the host word's high
// half became the lane id (multi-lane per-peer seqn streams): a
// mixed-build world (old sender, new receiver) would not error on
// size/magic but silently never match and surface as RECEIVE_TIMEOUT —
// the magic makes cross-version ranks fail fast at frame decode instead.
constexpr uint32_t MSG_MAGIC = 0xACC17B04u;

// Lane packing: the header's host word carries {lane:16, host:16}.
constexpr uint32_t WIRE_MAX_LANES = 2;  // 0 = default, 1 = bulk
inline uint32_t wire_pack_host(uint32_t host, uint32_t lane) {
  return (host & 0xFFFFu) | (lane << 16);
}
inline uint32_t wire_host(uint32_t host_word) { return host_word & 0xFFFFu; }
inline uint32_t wire_lane(const MsgHeader &h) { return h.host >> 16; }

// Payload bytes that follow this header on the wire (framing is derived
// from the header alone — no length prefix).
inline size_t wire_payload_len(const MsgHeader &h) {
  return (h.msg_type == MSG_EGR_DATA || h.msg_type == MSG_RNDZV_WRITE)
             ? (size_t)h.bytes
             : 0;
}

// A fully serialized frame (header immediately followed by payload) and
// the refcount that keeps it pinned: the retransmit buffer, the chaos
// reorder hold, and an in-flight TX batch all share ONE buffer — the
// frame's bytes are built exactly once and retained by reference until
// the last holder lets go (no second payload copy for retention).
using FrameBuf = std::vector<uint8_t>;
using FramePtr = std::shared_ptr<FrameBuf>;

// Borrowed scatter-gather view of one outbound frame. The header rides
// BY VALUE (stable storage for an iovec while the payload pointer is
// borrowed from caller memory); `contiguous` marks views over a
// serialized FrameBuf, where payload - sizeof(MsgHeader) is the buffer
// start and a legacy single-write may ship it without coalescing.
struct FrameView {
  MsgHeader h;
  const uint8_t *payload = nullptr;
  size_t payload_len = 0;
  bool contiguous = false;
};

inline FrameView frame_view(const FrameBuf &f) {
  FrameView v;
  std::memcpy(&v.h, f.data(), sizeof v.h);
  v.payload = f.data() + sizeof(MsgHeader);
  v.payload_len = f.size() - sizeof(MsgHeader);
  v.contiguous = true;
  return v;
}

}  // namespace acclw

#endif  // ACCLRT_WIRE_H
