// accl-tpu native runtime: reliability sublayer — CRC32C frame
// integrity (Castagnoli, the iSCSI/RDMA wire polynomial). Hardware
// SSE4.2 crc32 instructions when the host has them (one-time cpuid
// dispatch; ~an order of magnitude over the table walk — what keeps the
// no-fault CRC cost inside the chaos gate's 3% per-dispatch budget),
// byte-table fallback otherwise.

#include "reliability.h"

#include <cstring>
#include <mutex>

namespace acclw {
namespace {

constexpr uint32_t CRC32C_POLY = 0x82F63B78u;  // reflected Castagnoli

uint32_t g_crc32c_table[256];

void crc32c_table_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (CRC32C_POLY ^ (c >> 1)) : (c >> 1);
    g_crc32c_table[i] = c;
  }
}

uint32_t crc32c_sw(uint32_t crc, const uint8_t *p, size_t n) {
  for (size_t i = 0; i < n; i++)
    crc = g_crc32c_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc;
}

#if defined(__x86_64__)
// The crc32 instruction has ~3-cycle latency at 1/cycle throughput, so
// a single dependent chain runs at a third of the machine's rate —
// and the frame CRC is the dominant term of the reliability
// sublayer's no-fault budget. Standard remedy: run THREE independent
// lanes over adjacent blocks and splice them with the GF(2)
// "advance-over-N-zero-bytes" operator (CRC is linear: crc(A||B) =
// shift_|B|(crc(A)) ^ crc(B)), precomputed as 4x256 tables for the two
// block sizes. Measured ~2.5-3x over the single chain on the CI host —
// what holds the chaos gate's 3% per-dispatch bound at jumbo frames.
constexpr size_t CRC_LONG = 8192, CRC_SHORT = 256;  // powers of two
uint32_t g_crc_zeros_long[4][256];
uint32_t g_crc_zeros_short[4][256];

// GF(2) 32x32 matrix applied to a 32-bit vector (mat[i] = image of
// basis bit i).
uint32_t gf2_times(const uint32_t *mat, uint32_t vec) {
  uint32_t sum = 0;
  while (vec) {
    if (vec & 1) sum ^= *mat;
    vec >>= 1;
    mat++;
  }
  return sum;
}

void gf2_square(uint32_t *dst, const uint32_t *src) {
  for (int i = 0; i < 32; i++) dst[i] = gf2_times(src, src[i]);
}

// Build the 4x256 table form of the operator advancing a (reflected)
// CRC32C register over `len` zero bytes, len a power of two: the
// one-zero-BIT operator squared log2(8*len) times.
void crc32c_zeros(uint32_t zeros[4][256], size_t len) {
  uint32_t a[32], b[32];
  a[0] = CRC32C_POLY;
  for (int i = 1; i < 32; i++) a[i] = 1u << (i - 1);
  uint32_t *src = a, *dst = b;
  int squarings = 3;  // 8 bits = one byte
  for (size_t l = len; l > 1; l >>= 1) squarings++;
  for (int k = 0; k < squarings; k++) {
    gf2_square(dst, src);
    uint32_t *t = src;
    src = dst;
    dst = t;
  }
  for (int j = 0; j < 4; j++)
    for (uint32_t i = 0; i < 256; i++)
      zeros[j][i] = gf2_times(src, i << (8 * j));
}

inline uint32_t crc32c_shift(const uint32_t zeros[4][256], uint32_t crc) {
  return zeros[0][crc & 0xFF] ^ zeros[1][(crc >> 8) & 0xFF] ^
         zeros[2][(crc >> 16) & 0xFF] ^ zeros[3][crc >> 24];
}

__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const uint8_t *p, size_t n) {
  uint64_t c0 = crc;
  while (n >= 3 * CRC_LONG) {
    uint64_t c1 = 0, c2 = 0;
    const uint8_t *e = p + CRC_LONG;
    do {
      uint64_t v0, v1, v2;  // alignment-safe loads (UBSan-clean)
      std::memcpy(&v0, p, 8);
      std::memcpy(&v1, p + CRC_LONG, 8);
      std::memcpy(&v2, p + 2 * CRC_LONG, 8);
      c0 = __builtin_ia32_crc32di(c0, v0);
      c1 = __builtin_ia32_crc32di(c1, v1);
      c2 = __builtin_ia32_crc32di(c2, v2);
      p += 8;
    } while (p < e);
    c0 = crc32c_shift(g_crc_zeros_long, (uint32_t)c0) ^ (uint32_t)c1;
    c0 = crc32c_shift(g_crc_zeros_long, (uint32_t)c0) ^ (uint32_t)c2;
    p += 2 * CRC_LONG;
    n -= 3 * CRC_LONG;
  }
  while (n >= 3 * CRC_SHORT) {
    uint64_t c1 = 0, c2 = 0;
    const uint8_t *e = p + CRC_SHORT;
    do {
      uint64_t v0, v1, v2;
      std::memcpy(&v0, p, 8);
      std::memcpy(&v1, p + CRC_SHORT, 8);
      std::memcpy(&v2, p + 2 * CRC_SHORT, 8);
      c0 = __builtin_ia32_crc32di(c0, v0);
      c1 = __builtin_ia32_crc32di(c1, v1);
      c2 = __builtin_ia32_crc32di(c2, v2);
      p += 8;
    } while (p < e);
    c0 = crc32c_shift(g_crc_zeros_short, (uint32_t)c0) ^ (uint32_t)c1;
    c0 = crc32c_shift(g_crc_zeros_short, (uint32_t)c0) ^ (uint32_t)c2;
    p += 2 * CRC_SHORT;
    n -= 3 * CRC_SHORT;
  }
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c0 = __builtin_ia32_crc32di(c0, v);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = (uint32_t)c0;
  while (n--) c32 = __builtin_ia32_crc32qi(c32, *p++);
  return c32;
}
#endif

uint32_t (*g_crc32c_fn)(uint32_t, const uint8_t *, size_t) = crc32c_sw;
std::once_flag g_crc32c_once;

}  // namespace

uint32_t crc32c(uint32_t crc, const void *p, size_t n) {
  std::call_once(g_crc32c_once, [] {
    crc32c_table_init();
#if defined(__x86_64__)
    if (__builtin_cpu_supports("sse4.2")) {
      crc32c_zeros(g_crc_zeros_long, CRC_LONG);
      crc32c_zeros(g_crc_zeros_short, CRC_SHORT);
      g_crc32c_fn = crc32c_hw;
    }
#endif
  });
  return g_crc32c_fn(crc, (const uint8_t *)p, n);
}

// Whole-frame CRC: header with the crc field zeroed, then the payload.
uint32_t frame_crc(const MsgHeader &h, const void *payload, size_t plen) {
  MsgHeader tmp = h;
  tmp.crc = 0;
  uint32_t c = crc32c(0xFFFFFFFFu, &tmp, sizeof tmp);
  if (plen) c = crc32c(c, payload, plen);
  return c ^ 0xFFFFFFFFu;
}

}  // namespace acclw
