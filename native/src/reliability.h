// accl-tpu native runtime: reliability sublayer internals — frame
// integrity (CRC32C) and the retransmit-retention types the session's
// selective-retransmit machinery keys its state on.
//
// SEAM RULE: this header is session-side. transport.cpp must NOT
// include it (the POE seam carries already-built frames and knows
// nothing about CRC or retransmit policy) — `make -C native seamcheck`
// fails the build if it ever does.

#ifndef ACCLRT_RELIABILITY_H
#define ACCLRT_RELIABILITY_H

#include <chrono>
#include <cstddef>
#include <cstdint>
#include <deque>

#include "wire.h"

namespace acclw {

// CRC32C (Castagnoli, the iSCSI/RDMA wire polynomial). Hardware SSE4.2
// dispatch on first use; byte-table fallback otherwise (see
// reliability.cpp for the 3-lane GF(2)-spliced hot path).
uint32_t crc32c(uint32_t crc, const void *p, size_t n);

// Whole-frame CRC: header with the crc field zeroed, then the payload.
uint32_t frame_crc(const MsgHeader &h, const void *payload, size_t plen);

// ---------------------------------------------------------------------------
// Retransmit retention: per-(peer, lane) bounded buffer of sent frames,
// pinned BY REFERENCE (the FramePtr shares the serialized frame with the
// in-flight TX batch — building a frame never copies payload twice).
// GC'd by the peer's cumulative ACKs, evicted oldest-first at budget.
// ---------------------------------------------------------------------------
struct RetxFrame {
  uint32_t seqn;
  FramePtr bytes;  // serialized header+payload, shared with the TX path
};
struct RetxBuf {
  std::deque<RetxFrame> q;  // ascending seqn
  uint64_t bytes = 0;       // retained payload+header bytes (vs budget)
};

// REORDER injection: a frame the seeded chaos model holds back to swap
// with the next one to its (dst, lane) — same shared serialized bytes.
struct HeldFrame {
  FramePtr bytes;
  std::chrono::steady_clock::time_point since;
};

// Receiver-side NACK pacing state for one (peer, lane) seqn stream.
// want = the head seqn a consumer is provably waiting on (recorded at
// seek miss); NACKed with bounded exponential backoff.
struct WantState {
  bool active = false;
  uint32_t seqn = 0;
  uint32_t attempts = 0;
  std::chrono::steady_clock::time_point next_nack{};
};

}  // namespace acclw

#endif  // ACCLRT_RELIABILITY_H
