"""accl-tpu benchmark driver.

Mirrors the reference's sweep benchmark (test/host/xrt/src/bench.cpp:25-61:
2^4..2^19-element sweep per collective, cycle counts to CSV) adapted to
what the available hardware can honestly measure:

  - on a single TPU chip, cross-chip collectives have no wire, so the
    headline metric is the data plane: the reduce_ops combine lane
    (elementwise SUM of two fp32 buffers) swept 1 KB - 1 GB. The
    reference's data plane moves at most 64 B/cycle @ 250 MHz with a
    100 Gbps (12.5 GB/s) line rate (SURVEY.md §6) — vs_baseline is
    measured against that 12.5 GB/s bus ceiling.
  - with multiple devices visible (CPU emulation mesh or a real slice),
    the eager ring-allreduce schedule is also swept and reported to the
    detail CSV.

stdout: exactly ONE JSON line {metric, value, unit, vs_baseline}.
detail: accl_log/profile.csv (Test,Bytes,Seconds,GBps — the reference's
profile_<rank>.csv shape, fixture.hpp:145-151).

Modes: --smoke (CI fused-vs-eager gate + lint/telemetry overhead
budgets), --quant-gate (wire-byte reduction gate), --trace (the
telemetry lane: emit accl_log/trace.json + trace_chrome.json and gate
the calibrate_from_trace residual improvement — docs/observability.md).
"""

import json
import math
import os
import pathlib
import sys
import time

import numpy as np

BASELINE_GBPS = 12.5  # ACCL line rate: 100 Gbps per port (README.md:6)


def _fetch(x):
    """Force execution by pulling a few result elements to the host.
    (On the tunneled TPU platform block_until_ready returns before the
    computation finishes, so a data dependency is the only reliable
    barrier.)"""
    return np.asarray(x.ravel()[:4])


def _fetch_checksum(x):
    """Cross-check barrier: reduce the WHOLE result on device, then pull
    the scalar. The read cannot complete until every element exists, so
    if `_fetch`'s 4-element read ever returned before the full
    computation finished, timings taken under this barrier would exceed
    `_fetch` timings by the missing tail. (A strided sample would leave
    the unsampled elements unordered relative to the fetch — the full
    sum is the only read that provably orders after the whole result.)
    tools/fetch_barrier_check.py times both and commits the agreement
    note to accl_log/ (REPORT.md cites it)."""
    import jax.numpy as jnp

    r = x.ravel()
    return np.asarray(jnp.sum(r.astype(jnp.float32)))


def _time_once(fn, *args, iters=2):
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _fetch(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(min(times))


_baseline_cache = {}


def _fetch_baseline(jax):
    """Round-trip overhead of a minimal fetch (size-independent over the
    relay) and its run-to-run spread; compiled once per process. Returns
    (t0, noise): t0 = fastest observed round-trip, noise = observed
    jitter, the floor below which a measured excess is unresolvable."""
    if "t0" not in _baseline_cache:
        import jax.numpy as jnp

        f0 = jax.jit(lambda: jnp.zeros(4, jnp.float32))
        _fetch(f0())
        times = []
        for _ in range(5):
            t = time.perf_counter()
            _fetch(f0())
            times.append(time.perf_counter() - t)
        _baseline_cache["t0"] = min(times)
        _baseline_cache["noise"] = max(max(times) - min(times), 1e-6)
    return _baseline_cache["t0"], _baseline_cache["noise"]


def _timeit_loop(make_fn, args, op_est_sec, target=0.25, kmax=200_000,
                 jax=None):
    """Per-op seconds with a loop depth chosen so device time dominates
    the (hundreds of ms, noisy) relay overhead: run the op K times
    device-side, subtract the fetch baseline, divide by K.

    The depth is adaptive: when the measured excess over the baseline is
    lost in relay jitter (fast ops whose a-priori estimate was too high),
    K is raised — from the measured per-op time when one resolves, else
    geometrically — and the lane re-measured, until the device time
    dominates or K hits kmax. Rows that still do not resolve are flagged
    (resolved=False) so no caller publishes a jitter-floor quotient as
    bandwidth.  Returns (sec, k, snr, resolved)."""
    if os.environ.get("ACCL_BENCH_CPU_FALLBACK") == "1":
        target, kmax = 0.05, 2_000  # bounded effort off-TPU
    k = int(max(4, min(kmax, target / max(op_est_sec, 1e-7))))
    t0, noise = _fetch_baseline(jax)
    fk = make_fn(k)
    _fetch(fk(*args))  # compile + warm the lane once (deeper K re-runs
    # the same compiled program: traced-k loops and Python-chained
    # dispatch chains alike recompile nothing)
    rounds = 5
    for r in range(rounds):
        tk = _time_once(fk, *args)
        dev = tk - t0
        resolved = dev >= 8 * noise
        # the k the measurement ran at is the k reported: adjust only
        # when another round will actually re-measure
        if (k >= kmax or (resolved and dev >= min(target / 2, 16 * noise))
                or r == rounds - 1):
            break
        k = (int(min(kmax, max(k + 1, target / (dev / k)))) if resolved
             else min(kmax, k * 16))
        fk = make_fn(k)
    # snr: how far the TOTAL loop time sits above the fetch-noise
    # baseline (per-op seconds are meaningless when tk ~ t0)
    snr = tk / max(t0, 1e-9)
    # unresolved rows report the jitter-resolution floor (8*noise)/k —
    # an UPPER bound on the true per-op time (so derived GB/s is a lower
    # bound), never a raw sub-noise or negative quotient
    sec = (dev if resolved else max(dev, 8 * noise)) / k
    return sec, k, snr, resolved


def bench_combine(jax, sizes_bytes):
    """The reduce_ops lane: c = a + b elementwise, fp32."""
    import jax.numpy as jnp

    from jax import lax

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")

    def make_variant(op):
        # k rides in as a traced scalar (fori_loop lowers to a while):
        # ONE compile per (variant, size) however many adaptive-depth
        # rounds _timeit_loop takes
        run = jax.jit(
            lambda a, b, k: lax.fori_loop(0, k, lambda i, c: op(c, b), a)
        )

        def make_fn(k):
            return lambda a, b: run(a, b, jnp.int32(k))

        return make_fn

    variants = [("combine_sum_fp32", jnp.add)]  # the lane schedules execute
    if on_tpu:
        from accl_tpu.ops.pallas_kernels import combine_pallas

        variants.append(
            ("combine_sum_fp32_pallas",
             lambda c, b: combine_pallas(c, b, op="sum", interpret=False))
        )
        if os.environ.get("ACCL_BENCH_FULL") == "1":
            # on-chip VMEM-tile sweep for the Pallas lane (height AND
            # width): the streaming-regime winner becomes the next
            # default block shape
            for br, ln in ((2048, 128), (8192, 128),
                           (512, 1024), (1024, 1024), (256, 4096)):
                variants.append(
                    (f"combine_sum_fp32_pallas_br{br}_l{ln}",
                     lambda c, b, _br=br, _ln=ln: combine_pallas(
                         c, b, op="sum", interpret=False,
                         block_rows=_br, lanes=_ln))
                )

    rows = []
    for nbytes in sizes_bytes:
        n = nbytes // 4
        a = jax.device_put(np.random.default_rng(0).standard_normal(n)
                           .astype(np.float32))
        b = jax.device_put(np.random.default_rng(1).standard_normal(n)
                           .astype(np.float32))
        # crude estimate: 3x payload over ~300 GB/s HBM + kernel overhead
        est = 3 * nbytes / 300e9 + 3e-6
        for name, op in variants:
            if "_pallas" in name and nbytes < 256 * 1024 * 1024:
                continue  # plugin variants measured in the streaming regime
            sec, k, snr, resolved = _timeit_loop(
                make_variant(op), (a, b), est, kmax=50_000_000, jax=jax)
            gbps = nbytes / sec / 1e9
            rows.append((name, nbytes, sec, gbps, snr, resolved))
            print(f"  {name:26s} {nbytes:>12d} B  {sec*1e6:10.1f} us  "
                  f"{gbps:8.2f} GB/s  (K={k})", file=sys.stderr)
    return rows


def bench_collective(jax, op_name, sizes_bytes, world):
    """Time one compiled collective schedule over however many devices
    exist (the per-collective sweep of the reference's bench.cpp:25-61,
    one Test name per collective)."""
    from jax.sharding import Mesh

    from accl_tpu import CallOptions, DataType, Operation, ReduceFunction, TuningParams
    from accl_tpu.sequencer import select_algorithm
    from accl_tpu.sequencer.lowering import ScheduleCompiler

    op = Operation[op_name]
    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    comp = ScheduleCompiler(mesh)
    rows = []
    for nbytes in sizes_bytes:
        count = nbytes // 4
        opts = CallOptions(scenario=op, count=count, root_src_dst=0,
                           function=int(ReduceFunction.SUM),
                           data_type=DataType.float32)
        plan = select_algorithm(
            op, count, 4, world,
            max_eager_size=1 << 30, eager_rx_buf_size=1 << 22,
            tuning=TuningParams.default(),
        )
        base_fn = comp.lower(opts, plan)
        import jax as _j

        # the repeat loop chains output into input only for ops whose
        # output shape matches the input; other ops still dispatch k
        # independent times (per-op seconds are the mean over k either way)
        same_shape = op in (Operation.allreduce, Operation.bcast,
                            Operation.reduce, Operation.alltoall)

        # multi-device CPU worlds sync every dispatch: deep async queues
        # of multi-device programs starve XLA's in-process CPU rendezvous
        # (worker threads service later-enqueued programs while earlier
        # participants wait — observed as collective-permute termination
        # timeouts at k~200, world 8). The ops are ms-scale there, so the
        # per-dispatch sync does not distort the measurement. Real-TPU
        # worlds keep the pipelined chain: hardware collectives are
        # us-scale and a host sync per dispatch would dominate them.
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
        sync_each = world > 1 and not on_tpu

        def make_fn(k, _f=base_fn, _same=same_shape, _sync=sync_each):
            def rep(x):
                if _same:
                    for _ in range(k):
                        x = _f(x)
                        if _sync:
                            jax.block_until_ready(x)
                    return x
                out = None
                for _ in range(k):
                    out = _f(x)
                    if _sync:
                        jax.block_until_ready(out)
                    else:
                        # per-row (sharding-aligned, collective-free) data
                        # dependency serializes dispatches like the chained
                        # lane and bounds in-flight outputs to one buffer
                        x = x + (out[..., :1] * 0).astype(x.dtype)
                return out
            return rep

        x = np.random.default_rng(2).standard_normal((world, count)) \
            .astype(np.float32)
        xd = _j.device_put(x)
        est = 2 * nbytes / 20e9 + 1e-4
        sec, _k, snr, resolved = _timeit_loop(make_fn, (xd,), est,
                                              target=0.5, kmax=200, jax=_j)
        if world > 1:
            # bus bandwidth convention for allreduce; payload/s elsewhere
            scale = (2 * (world - 1) / world
                     if op == Operation.allreduce else 1.0)
            bw = scale * nbytes / sec / 1e9
            name = f"{op_name}_w{world}_fp32"
        else:
            # single chip (the real-TPU regime): no wire exists, so this
            # times the COMPILED program's dispatch + datapath (the
            # world-1 degenerate schedule); multi-rank wire numbers come
            # from the emulator sweep (accl_log/emu_bench.csv)
            bw = nbytes / sec / 1e9
            name = f"{op_name}_w1_dispatch_datapath_fp32"
        rows.append((name, nbytes, sec, bw, snr, resolved))
        print(f"  {name} {nbytes:>10d} B  {sec*1e6:10.1f} us  "
              f"{bw:8.2f} GB/s", file=sys.stderr)
    return rows


def bench_sequence(jax, world, n_elems=8192, iters=30):
    """Fused call sequence vs eager back-to-back dispatch: the SAME
    3-collective chain (reduce_scatter -> allgather -> bcast) issued as
    one recorded sequence (ONE compiled program, one dispatch) and as
    three facade calls (three dispatches + HBM seams). The chain is
    dispatch-dominated at this size, which is exactly the cost the
    sequence layer exists to amortize. Emits sequence_eager /
    sequence_fused rows plus a sequence_fused_vs_eager row whose value
    column is the speedup (eager_sec / fused_sec)."""
    from jax.sharding import Mesh

    from accl_tpu import ReduceFunction
    from accl_tpu.accl import ACCL

    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    accl = ACCL(mesh)
    n = (n_elems // world) * world
    chunk = n // world
    rng = np.random.default_rng(7)
    x = rng.standard_normal((world, n)).astype(np.float32)
    a = accl.create_buffer(n, data=x)
    b = accl.create_buffer(chunk)
    c = accl.create_buffer(n)

    def eager_once():
        accl.reduce_scatter(a, b, chunk, ReduceFunction.SUM,
                            from_device=True, to_device=True)
        accl.allgather(b, c, chunk, from_device=True, to_device=True)
        return accl.bcast(c, n, 0, from_device=True, to_device=True)

    def fused_once():
        seq = accl.sequence()
        seq.reduce_scatter(a, b, chunk, ReduceFunction.SUM)
        seq.allgather(b, c, chunk)
        seq.bcast(c, n, 0)
        return seq.run(from_device=True, to_device=True)

    # warm both paths (compiles happen here; the timed loops below hit
    # the schedule caches only)
    eager_once().wait()
    req = fused_once()
    req.wait()
    assert req.num_dispatches == 1 and req.num_steps == 3

    def time_path(once):
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            once().wait()
            times.append(time.perf_counter() - t0)
        # median: multi-device CPU dispatch has heavy outliers
        return float(np.median(times))

    sec_eager = time_path(eager_once)
    sec_fused = time_path(fused_once)
    speedup = sec_eager / sec_fused
    nbytes = n * 4
    rows = [
        (f"sequence_eager_w{world}_fp32", nbytes, sec_eager,
         nbytes / sec_eager / 1e9, 1.0, True),
        (f"sequence_fused_w{world}_fp32", nbytes, sec_fused,
         nbytes / sec_fused / 1e9, 1.0, True),
        # value column carries the SPEEDUP, not a bandwidth
        ("sequence_fused_vs_eager", nbytes, sec_fused, speedup, 1.0, True),
    ]
    print(f"  sequence 3-coll w{world}: eager {sec_eager*1e6:9.1f} us  "
          f"fused {sec_fused*1e6:9.1f} us  speedup {speedup:5.2f}x  "
          f"(1 dispatch vs 3)", file=sys.stderr)
    return rows, speedup


def bench_ring_overlap(jax, world, nbytes=64 * 1024 * 1024):
    """Segmented Pallas ring allreduce: slot-overlapped (default) vs
    serialized segments, at a payload large enough to span many
    PALLAS_RING_MAX_BYTES segments. Only meaningful where the fused ICI
    kernel actually runs (real TPU); interpret mode at 64 MiB is not an
    honest measurement, so the lane is skipped off-chip."""
    if jax.devices()[0].platform not in ("tpu", "axon"):
        print("  ring-overlap lane skipped (no TPU attached)",
              file=sys.stderr)
        return []
    from jax.sharding import Mesh

    from accl_tpu import CallOptions, DataType, Operation, ReduceFunction, TuningParams
    from accl_tpu.sequencer import select_algorithm
    from accl_tpu.sequencer.lowering import ScheduleCompiler

    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    count = nbytes // 4
    opts = CallOptions(scenario=Operation.allreduce, count=count,
                       function=int(ReduceFunction.SUM),
                       data_type=DataType.float32)
    plan = select_algorithm(Operation.allreduce, count, 4, world,
                            max_eager_size=1 << 30,
                            eager_rx_buf_size=1 << 22,
                            tuning=TuningParams.default())
    x = jax.device_put(np.random.default_rng(3)
                       .standard_normal((world, count)).astype(np.float32))
    rows = []
    for name, overlap in (("allreduce_pallas_serialized", False),
                          ("allreduce_pallas_overlap", True)):
        comp = ScheduleCompiler(mesh, use_pallas_ring=True,
                                pallas_ring_overlap=overlap)
        fn = comp.lower(opts, plan)
        _fetch(fn(x))  # compile + warm
        sec = _time_once(fn, x, iters=3)
        bw = 2 * (world - 1) / world * nbytes / sec / 1e9
        rows.append((f"{name}_w{world}_fp32", nbytes, sec, bw, 1.0, True))
        print(f"  {name}_w{world} {nbytes:>10d} B  {sec*1e6:10.1f} us  "
              f"{bw:8.2f} GB/s", file=sys.stderr)
    return rows


def measure_lint_overhead(jax, world, n_elems=8192, iters=20):
    """The lint stage's cost against the record+compile time it guards:
    record the smoke chain on a FRESH ACCL (cold caches), time its
    first run (lowering + XLA compile) with lint off, then time the
    same batch through the analyzer — the FULL default tier, semantic
    certification included (plans passed, so the contribution-set pass
    runs; its verdicts cache by static signature exactly as they do
    in-band, and the warm path is what every re-recorded batch pays).
    Returns (lint_sec, record_compile_sec, ratio). The smoke gate
    asserts ratio < 0.05 — the static gate must stay invisible next to
    the compile it fronts."""
    from jax.sharding import Mesh

    from accl_tpu import ReduceFunction
    from accl_tpu.accl import ACCL
    from accl_tpu.analysis.linter import SequenceLinter
    from accl_tpu.constants import (
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        DEFAULT_MAX_RENDEZVOUS_SIZE,
        TuningParams,
        dtype_nbytes,
    )
    from accl_tpu.sequencer.plan import select_algorithm

    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    accl = ACCL(mesh)
    n = (n_elems // world) * world
    chunk = n // world
    a = accl.create_buffer(n)
    b = accl.create_buffer(chunk)
    c = accl.create_buffer(n)

    t0 = time.perf_counter()
    seq = accl.sequence(lint="off")
    seq.reduce_scatter(a, b, chunk, ReduceFunction.SUM)
    seq.allgather(b, c, chunk)
    seq.bcast(c, n, 0)
    steps = list(seq.calls)
    seq.run(from_device=True, to_device=True).wait()
    record_compile = time.perf_counter() - t0

    linter = SequenceLinter(world)  # the in-band (default) configuration
    plans = [select_algorithm(
        o.scenario, o.count, dtype_nbytes(o.data_type), world,
        o.compression_flags, o.stream_flags,
        max_eager_size=DEFAULT_MAX_EAGER_SIZE,
        eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE,
        tuning=TuningParams.default(DEFAULT_MAX_RENDEZVOUS_SIZE),
        compress_dtype=o.compress_dtype) for o in steps]
    widths = {o.addr_0: n for o in steps} | {steps[0].addr_2: chunk}
    linter.lint(steps, plans, buffer_widths=widths)  # warm imports+caches
    lint_sec = min(
        _time_wall(lambda: linter.lint(steps, plans,
                                       buffer_widths=widths))
        for _ in range(iters))
    return lint_sec, record_compile, lint_sec / record_compile


def measure_interference_overhead(jax, world, n_elems=8192, iters=20):
    """The cross-program footprint layer's cost against the
    record+compile time it rides: footprint extraction happens inside
    EVERY prepare_sequence, and certify_concurrent's pairwise check is
    what a multi-tenant admission pays per proposed set. Times (a) a
    cold footprint_from_steps over the smoke chain's descriptors plus
    (b) an uncached pairwise certify of two disjoint such programs
    (fresh certifier each iter — the cached path is ~a dict hit and
    would measure nothing). Returns (layer_sec, record_compile_sec,
    ratio); the smoke gate asserts ratio < 0.05, same budget as the
    lint stage — summaries must stay invisible next to the compile."""
    from jax.sharding import Mesh

    from accl_tpu import ReduceFunction
    from accl_tpu.accl import ACCL
    from accl_tpu.analysis.interference import (InterferenceCertifier,
                                                footprint_from_steps)

    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    accl = ACCL(mesh)
    n = (n_elems // world) * world
    chunk = n // world

    def record_chain():
        a = accl.create_buffer(n)
        b = accl.create_buffer(chunk)
        c = accl.create_buffer(n)
        t0 = time.perf_counter()
        seq = accl.sequence(lint="off")
        seq.reduce_scatter(a, b, chunk, ReduceFunction.SUM)
        seq.allgather(b, c, chunk)
        seq.bcast(c, n, 0)
        steps = list(seq.calls)
        seq.run(from_device=True, to_device=True).wait()
        return steps, time.perf_counter() - t0

    steps_a, record_compile = record_chain()
    steps_b, _ = record_chain()  # disjoint buffers: the clean fast path

    def layer():
        fa = footprint_from_steps(steps_a, world, label="A")
        fb = footprint_from_steps(steps_b, world, label="B")
        cert = InterferenceCertifier()  # cold cache: full pairwise cost
        diags = cert.certify([fa, fb])
        assert not diags and cert.escalations == 0

    layer()  # warm imports
    layer_sec = min(_time_wall(layer) for _ in range(iters))
    return layer_sec, record_compile, layer_sec / record_compile


def _time_wall(fn):
    t = time.perf_counter()
    fn()
    return time.perf_counter() - t


def _jaxpr_ppermute_bytes(jaxpr) -> int:
    """Sum the operand bytes of every ppermute equation in a (closed)
    jaxpr: the static measure of bytes-on-the-wire per rank for one
    execution of the traced program. Rides the analysis package's
    walker (every cross-rank hop in the schedule layer IS a ppermute —
    the protocol pass leans on the same invariant)."""
    import jax.core as jcore

    from accl_tpu.analysis.protocol import iter_ppermute_eqns

    return sum(v.aval.size * v.aval.dtype.itemsize
               for eqn in iter_ppermute_eqns(jaxpr)
               for v in eqn.invars
               if not isinstance(v, jcore.Literal))


def bench_quantized_wire(jax, world, nbytes=16 * 1024 * 1024,
                         err_elems=1 << 16):
    """The quantized-allreduce gate lane: trace the fp32 and the
    blockwise-int8-wire ring allreduce at `nbytes` payload and compare
    TOTAL ppermute operand bytes (the wire bytes every hop moves,
    measured from the lowered program itself, not from the model), then
    execute a smaller quantized allreduce against the fp32 oracle for
    the max relative error. Returns (reduction_x, max_rel_err)."""
    from jax.sharding import Mesh

    from accl_tpu import (CallOptions, CompressionFlags, DataType,
                          Operation, ReduceFunction, TuningParams)
    from accl_tpu.sequencer import select_algorithm
    from accl_tpu.sequencer.lowering import ScheduleCompiler

    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    comp = ScheduleCompiler(mesh, use_pallas_ring=False)
    count = nbytes // 4
    kw = dict(max_eager_size=1 << 30, eager_rx_buf_size=1 << 22,
              tuning=TuningParams.default())

    def traced_bytes(wire):
        flags = (CompressionFlags.ETH_COMPRESSED if wire != DataType.none
                 else CompressionFlags.NO_COMPRESSION)
        opts = CallOptions(scenario=Operation.allreduce, count=count,
                           function=int(ReduceFunction.SUM),
                           compression_flags=flags,
                           data_type=DataType.float32, compress_dtype=wire)
        plan = select_algorithm(Operation.allreduce, count, 4, world,
                                flags, compress_dtype=wire, **kw)
        fn = comp.lower(opts, plan)
        arg = jax.ShapeDtypeStruct((world, count), np.float32)
        return _jaxpr_ppermute_bytes(jax.make_jaxpr(fn)(arg))

    b_fp32 = traced_bytes(DataType.none)
    b_q = traced_bytes(DataType.int8)
    reduction = b_fp32 / max(b_q, 1)

    # numeric lane: quantized vs fp32 oracle at a size small enough for
    # the CPU mesh, same plan family as the 16 MiB trace
    flags = CompressionFlags.ETH_COMPRESSED
    opts = CallOptions(scenario=Operation.allreduce, count=err_elems,
                       function=int(ReduceFunction.SUM),
                       compression_flags=flags,
                       data_type=DataType.float32,
                       compress_dtype=DataType.int8)
    plan = select_algorithm(Operation.allreduce, err_elems, 4, world,
                            flags, compress_dtype=DataType.int8, **kw)
    fn = comp.lower(opts, plan)
    x = np.random.default_rng(11).standard_normal(
        (world, err_elems)).astype(np.float32)
    out = np.asarray(fn(x))
    oracle = x.sum(0)
    scale = np.abs(oracle).max()
    max_rel = float(np.abs(out[0] - oracle).max() / scale)
    print(f"  quantized_allreduce w{world}: wire {b_fp32 / 2**20:.1f} MiB "
          f"-> {b_q / 2**20:.1f} MiB per rank ({reduction:.2f}x), "
          f"max rel err {max_rel:.2e} at {err_elems * 4 // 1024} KiB",
          file=sys.stderr)
    return reduction, max_rel


def _moe_harness(jax, world, payload_bytes, *, tuned):
    """The MoE layer-step harness for the moe_dispatch lanes: an ACCL
    over `world` CPU-mesh devices with the expert-FFN consumer
    registered, sized so the per-peer alltoall chunk is
    ~`payload_bytes`. `tuned=True` applies the measured
    ALLTOALL_COMPRESS_MIN_COUNT register (the autotune path: crossover
    from the shipped calibrated link), so the fused path's int8 wire is
    a register-selected decision, not a hand-set flag; `tuned=False` is
    the eager fp32 baseline device (register 0 = exact wire,
    bit-for-bit default selection). Returns a dict with the accl,
    buffers, shapes and a one-dispatch `step(fused=)` callable."""
    from jax.sharding import Mesh

    from accl_tpu.accl import ACCL
    from accl_tpu.constants import TuningParams
    from accl_tpu.models.moe import (
        MOE_EXPERT_STREAM,
        MoEConfig,
        create_moe_layer_buffers,
        make_expert_program,
        make_moe_layer_program,
        moe_expert_consumer,
        run_moe_layer,
    )
    from accl_tpu.sequencer.timing import tuning_crossovers

    D = 64
    C = max(payload_bytes // 4 // D, 1)
    count = C * D
    mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
    accl = ACCL(mesh)
    cfg = MoEConfig(d_model=D, d_ff=2 * D, n_experts=world,
                    experts_per_rank=1)
    if tuned:
        link = _shipped_link()
        cross = tuning_crossovers(link, world=world)
        reg = int(cross["alltoall_compress_min_bytes"])
        if not 0 < reg <= count * 4:
            raise SystemExit(
                f"FAIL: moe_dispatch lane unavailable: the calibrated "
                f"alltoall compress window ({reg} B) does not cover the "
                f"{count * 4} B cell; re-run tools/timing_model.py / "
                "--write-baseline if the link legitimately moved")
        # the defaults PLUS the one register — a bare TuningParams(...)
        # would zero every other selection register on this device
        tuned_tp = TuningParams.default()
        tuned_tp.alltoall_compress_min_count = reg
        accl.configure_tuning_parameters(tuned_tp)
    rng = np.random.default_rng(7)
    w_up = rng.standard_normal((world, D, 2 * D)).astype(np.float32) * 0.1
    w_down = rng.standard_normal((world, 2 * D, D)).astype(np.float32) * 0.1
    accl.register_stream_consumer(
        MOE_EXPERT_STREAM,
        moe_expert_consumer(cfg, C, w_up, w_down, accl.axis_name))
    disp, mid, out = create_moe_layer_buffers(accl, cfg, C)
    disp.write(rng.standard_normal(
        (world, world * count)).astype(np.float32))
    disp.sync_to_device()
    expert_prog = make_expert_program(accl, cfg, C, w_up, w_down)
    program = make_moe_layer_program(accl, disp, mid, out, count)

    def step(mode):
        """One layer step, steady-state convention: inputs already on
        device, results left on device (a training/serving loop keeps
        activations resident — from/to_device on every path, so the
        measured ratios compare dispatch/wire structure, not common
        host-copy bookkeeping). "fused" = ONE dispatch of the prepared
        layer-step program, "eager2" = the same two descriptors issued
        eagerly (spliced consumer, the bitwise twin), "eager3" = the
        descriptor-per-stage pre-fusion baseline (dispatch alltoall /
        standalone expert program / combine alltoall, three
        dispatches). Callers wanting host results sync `out`
        explicitly."""
        if mode == "fused":
            program.run(from_device=True, to_device=True)
        else:
            run_moe_layer(accl, disp, mid, out, count, fused=False,
                          expert_fn=expert_prog if mode == "eager3"
                          else None, from_device=True, to_device=True)
        return out.device

    return dict(accl=accl, cfg=cfg, C=C, D=D, count=count, step=step,
                bufs=(disp, mid, out), weights=(w_up, w_down))


def _moe_traced_wire_bytes(world, count, C, D, wire):
    """ppermute bytes-on-wire of ONE fused MoE layer-step program
    (dispatch alltoall + expert consumer + combine alltoall as a single
    SequencePlan body), traced — the static audit
    `--moe-gate` compares fp32 vs int8 on."""
    import jax

    from accl_tpu.constants import (
        CompressionFlags,
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        DataType,
        Operation,
        StreamFlags,
        TuningParams,
    )
    from accl_tpu.descriptor import CallOptions, SequenceDescriptor
    from accl_tpu.models.moe import MoEConfig, moe_expert_consumer
    from accl_tpu.sequencer.lowering import AxisOnlyMesh, ScheduleCompiler
    from accl_tpu.sequencer.plan import select_algorithm
    from accl_tpu.sequencer.sequence import SequencePlan

    cfg = MoEConfig(d_model=D, d_ff=2 * D, n_experts=world,
                    experts_per_rank=1)
    consumer = moe_expert_consumer(
        cfg, C, np.zeros((world, D, 2 * D), np.float32),
        np.zeros((world, 2 * D, D), np.float32))
    flags = (CompressionFlags.ETH_COMPRESSED if wire != DataType.none
             else CompressionFlags.NO_COMPRESSION)

    def opts(a0, a2, streamed):
        return CallOptions(
            scenario=Operation.alltoall, count=count,
            data_type=DataType.float32, compress_dtype=wire,
            compression_flags=flags,
            stream_flags=(StreamFlags.RES_STREAM if streamed
                          else StreamFlags.NO_STREAM),
            res_stream_id=11 if streamed else 0, addr_0=a0, addr_2=a2)

    desc = SequenceDescriptor((opts(1, 2, True), opts(2, 3, False)))
    kw = dict(max_eager_size=DEFAULT_MAX_EAGER_SIZE,
              eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE,
              tuning=TuningParams.default())
    plans = [select_algorithm(o.scenario, o.count, 4, world,
                              o.compression_flags, o.stream_flags,
                              compress_dtype=wire, **kw)
             for o in desc.steps]
    seq = SequencePlan(desc, plans, world,
                       endpoints=[(None, consumer), (None, None)])
    comp = ScheduleCompiler(AxisOnlyMesh("ccl", world), "ccl",
                            use_pallas_ring=False)
    body, n_in = seq.build(comp)
    avals = [jax.ShapeDtypeStruct((world * count,), np.float32)] * n_in
    closed = jax.make_jaxpr(body, axis_env=[("ccl", world)])(*avals)
    return _jaxpr_ppermute_bytes(closed)


def _moe_predicted_times(world, count, payload_bytes):
    """(eager_fp32_s, fused_int8_s) for the layer step's two alltoall
    legs under the SHIPPED calibrated link (aggregate cost shape — the
    regime the emulator fit calibrates): the eager side pays fp32 wire
    bytes and three program dispatches, the fused side int8 wire bytes
    and one. The expert FFN itself is identical compute on both sides
    and cancels out of the ratio, so it is charged to neither. This is
    the SAME model every selection register in the repo is derived
    from and that bench --trace/--check continuously validate against
    measurement — the time claim for the quantized wire lives here
    because the CPU mesh HAS no wire (its ppermute is a memcpy), so
    int8's 3.94x byte cut is invisible to wall clock there by
    construction (the same physics the hier gate's WAN shaper exists
    to fix on the native side)."""
    from accl_tpu.constants import (
        CompressionFlags,
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        DataType,
        Operation,
        TuningParams,
    )
    from accl_tpu.sequencer.plan import select_algorithm
    from accl_tpu.sequencer.timing import predict_sequence

    link = _shipped_link()
    kw = dict(max_eager_size=DEFAULT_MAX_EAGER_SIZE,
              eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE,
              tuning=TuningParams.default())

    def leg_plan(wire):
        comp = (CompressionFlags.ETH_COMPRESSED if wire != DataType.none
                else CompressionFlags.NO_COMPRESSION)
        return select_algorithm(Operation.alltoall, count, 4, world, comp,
                                compress_dtype=wire, **kw)

    def t(wire, fused):
        calls = [(Operation.alltoall, leg_plan(wire), count, 4)] * 2
        n_dispatch_extra = 0 if fused else 1  # the expert stage's own
        # dispatch rides the eager side (it is fused into the one
        # program on the fused side); its compute cancels either way
        sec = predict_sequence(
            link, calls, world, rx_buf_bytes=DEFAULT_EAGER_RX_BUF_SIZE,
            aggregate=True, dispatch_alpha=link.alpha, fused=fused)
        return sec + n_dispatch_extra * link.alpha

    return t(DataType.none, fused=False), t(DataType.int8, fused=True)


def bench_moe_dispatch(jax, world, payload_bytes=8 * 1024, rounds=40):
    """The moe_dispatch gate lane. Three claims, each measured where it
    is honestly measurable (the same split the quant and hier gates
    use):

      1. WIRE BYTES (traced): the fused+quantized layer-step program
         ships <= 1/2 the eager fp32 baseline's ppermute bytes — read
         from the lowered programs themselves.
      2. FUSION (measured, equal wire): ONE dispatch of the prepared
         layer-step program beats the descriptor-per-stage eager form
         (dispatch alltoall / standalone expert program / combine
         alltoall, three dispatches) at the SAME int8 wire, interleaved
         medians on the CPU mesh.
      3. QUANTIZED WIRE (calibrated link): fused+int8 vs eager fp32
         under the shipped calibrated LinkParams — the CPU mesh's
         "wire" is a memcpy, so the byte win shows up in wall time only
         through the link model every other selection decision already
         rides; the measured fp32-vs-int8 parity ratio is reported
         unvarnished alongside it.

    Also asserts the fused fp32 path is BITWISE-identical to issuing
    the same two descriptors eagerly, and the int8 result within the
    documented per-block bound. Returns a result dict."""
    from accl_tpu.constants import DataType

    tuned = _moe_harness(jax, world, payload_bytes, tuned=True)
    plain = _moe_harness(jax, world, payload_bytes, tuned=False)
    count, C, D = tuned["count"], tuned["C"], tuned["D"]

    b_fp32 = _moe_traced_wire_bytes(world, count, C, D, DataType.none)
    b_int8 = _moe_traced_wire_bytes(world, count, C, D, DataType.int8)
    wire_ratio = b_fp32 / max(b_int8, 1)

    # correctness before speed: fused fp32 == same-descriptors-eager
    # fp32 BITWISE on the SAME device (plain: register off), and the
    # quantized fused result stays within the documented per-block
    # bound of the fp32 one
    ref = np.array(plain["step"]("eager2"), copy=True)
    np.testing.assert_array_equal(np.asarray(plain["step"]("fused")), ref)
    out_q = np.asarray(tuned["step"]("fused"))
    scale = max(np.abs(ref).max(), 1e-9)
    max_rel = float(np.abs(out_q - ref).max() / scale)

    # measured lane: warm every compiled program, then interleave one
    # dispatch per path per round and take medians (a load burst lands
    # on every side of every ratio)
    paths = {"fused_int8": lambda: tuned["step"]("fused"),
             "eager3_int8": lambda: tuned["step"]("eager3"),
             "eager3_fp32": lambda: plain["step"]("eager3")}
    for fn in paths.values():
        for _ in range(3):
            fn()
    samples: dict = {k: [] for k in paths}
    for _ in range(rounds):
        for name, fn in paths.items():
            t0 = time.perf_counter()
            fn()
            samples[name].append(time.perf_counter() - t0)
    sec = {k: float(np.median(v)) for k, v in samples.items()}
    fusion_x = sec["eager3_int8"] / sec["fused_int8"]
    parity_x = sec["eager3_fp32"] / sec["fused_int8"]
    pred_eager, pred_fused = _moe_predicted_times(world, count,
                                                  payload_bytes)
    pred_x = pred_eager / max(pred_fused, 1e-12)
    print(f"  moe_dispatch w{world}: wire {b_fp32 / 2**20:.2f} MiB -> "
          f"{b_int8 / 2**20:.2f} MiB ({wire_ratio:.2f}x); fused+int8 "
          f"{sec['fused_int8'] * 1e3:.2f} ms vs eager+int8 "
          f"{sec['eager3_int8'] * 1e3:.2f} ms ({fusion_x:.2f}x) vs "
          f"eager fp32 {sec['eager3_fp32'] * 1e3:.2f} ms "
          f"({parity_x:.2f}x, memcpy-wire mesh); calibrated-link "
          f"predicted {pred_x:.2f}x; max rel err {max_rel:.2e}",
          file=sys.stderr)
    return dict(wire_ratio=wire_ratio, fusion_x=fusion_x,
                parity_x=parity_x, pred_x=pred_x, max_rel=max_rel,
                sec=sec)


def _overlap_cfg(jax, scale: float = 1.0):
    """The overlap-gate transformer: parameters dominated by the
    embed/unembed pair (a ~1.5 MB gradient) while the token count
    stays tiny (so the per-rank fwd+bwd is single-digit ms on the CPU
    mesh). Sized for the regime where the overlap claim is ROBUST
    across host speeds: per-stripe wire bytes well under the shaped
    link's 2(P-1) hop alphas, so the serial form is paced by S chains
    of serialized hop LATENCY — exactly what the overlapped pipeline
    amortizes — rather than by bytes (which compute-vs-rate host
    variance would squeeze toward the 2x cap). `scale` shrinks the
    vocab for the compute-calibration sweep's second size (a
    ComputeFit needs two distinct gradient sizes)."""
    from accl_tpu.models.transformer import TransformerConfig

    return TransformerConfig(vocab=int(2560 * scale), d_model=64,
                             n_heads=4, n_layers=2, d_ff=128)


def _overlap_harness(jax, world, cfg, tokens, targets, *, serial,
                     overlap_reg, lr=1e-3):
    """One side of the overlap A/B: an ACCL over `world` CPU-mesh
    devices with the train-step consumer registered and the
    OVERLAP_MIN_COUNT register set to `overlap_reg`. serial=True
    builds the serial dispatch->compute twin — the compiler's
    overlap_serialize flag orders the stripe chains, and `step()`
    issues the SAME three descriptors eagerly (compute program, then
    allreduce, then update: three dispatches). serial=False compiles
    the ONE-dispatch fused program whose striped allreduce overlaps
    the backward. Both sides run the identical register-selected plan,
    so their results are bitwise-identical at fp32."""
    from jax.sharding import Mesh

    from accl_tpu.accl import ACCL
    from accl_tpu.constants import TuningParams
    from accl_tpu.models import transformer as trf

    saved = os.environ.get("ACCL_OVERLAP_SERIALIZE")
    os.environ["ACCL_OVERLAP_SERIALIZE"] = "1" if serial else "0"
    try:
        mesh = Mesh(np.array(jax.devices()[:world]), ("ccl",))
        accl = ACCL(mesh)
    finally:
        if saved is None:
            os.environ.pop("ACCL_OVERLAP_SERIALIZE", None)
        else:
            os.environ["ACCL_OVERLAP_SERIALIZE"] = saved
    # the defaults PLUS the one register (a bare TuningParams(...)
    # would zero every other selection register on this device)
    tp = TuningParams.default()
    tp.overlap_min_count = int(overlap_reg)
    accl.configure_tuning_parameters(tp)
    bufs = trf.create_train_step_buffers(accl, cfg)
    n = trf.train_param_count(cfg)
    init = np.tile(
        np.asarray(trf.flatten_train_params(
            trf.init_params(cfg, jax.random.key(3)))), (world, 1))
    bufs[0].write(init)
    bufs[0].sync_to_device()
    if serial:
        trf._register_train_consumers(accl, cfg, tokens, targets, lr)

        def step():
            trf.run_train_step_eager(accl, cfg, bufs)
            return bufs[3].device

        prog = None
    else:
        prog, _ = trf.make_train_step_program(accl, cfg, tokens,
                                              targets, lr=lr,
                                              buffers=bufs)

        def step():
            prog.run(from_device=True, to_device=True)
            return bufs[3].device

    return dict(accl=accl, bufs=bufs, step=step, prog=prog, n=n)


def _overlap_compute_calibration(jax, world, sizes=(0.5, 1.0), iters=3):
    """The compute-term sweep: time the train step's fwd+bwd program
    (the eager compute stage alone — copy with the grad consumer
    spliced) at two model sizes, emit one compute-tagged span per
    measurement, and refit timing.ComputeFit from the trace
    (telemetry.feedback.calibrate_compute_from_trace) — the busy-core
    term of the overlap pipeline, measured, never assumed. Returns
    (fit, trace)."""
    from accl_tpu.models import transformer as trf
    from accl_tpu.telemetry import (calibrate_compute_from_trace,
                                    get_tracer, validate_trace)

    tr = get_tracer()
    tr.enable()
    rng = np.random.default_rng(23)
    for scale in sizes:
        cfg = _overlap_cfg(jax, scale)
        tokens = rng.integers(0, cfg.vocab, (world, 1, 8)) \
            .astype(np.int32)
        targets = np.roll(tokens, -1, axis=2)
        h = _overlap_harness(jax, world, cfg, tokens, targets,
                             serial=True, overlap_reg=0)
        nbytes = h["n"] * 4
        pbuf, gbuf = h["bufs"][0], h["bufs"][1]

        # time ONLY the compute stage: the copy+consumer dispatch
        def compute_stage():
            h["accl"].copy_to_stream(
                pbuf, h["n"], res_stream=trf.TRAIN_GRAD_STREAM,
                dstbuf=gbuf, from_device=True, to_device=True)

        compute_stage()  # compile + warm
        for _ in range(iters):
            with tr.span("train_bwd", cat="compute",
                         track="bench") as sp:
                compute_stage()
                sp.set(compute_bytes=nbytes)
    trace = tr.to_trace({"world": world, "cost_shape": "aggregate"})
    validate_trace(trace)
    fit = calibrate_compute_from_trace(trace)
    # tracing stays OFF for the measured A/B that follows: the serial
    # side dispatches three traced programs per step vs the fused
    # side's one, so leaving the tracer armed would pad the serial
    # medians asymmetrically
    tr.disable()
    return fit, trace


def _overlap_gate_main():
    """bench.py --overlap-gate: compute-communication overlap as a
    MEASURED plan dimension, on the first full-model train-step
    workload in the repo (transformer fwd+bwd+grad-allreduce+SGD as
    ONE recorded descriptor batch). Four legs, the hier/moe gate
    discipline:

      1. CALIBRATE: time the fwd+bwd program at two model sizes, refit
         the ComputeFit compute term from the emitted telemetry spans,
         and persist it into accl_log/timing_model.json
         ("compute_fit") — the calibration ACCL.autotune and
         bench --check's train cells read back.
      2. REGISTER: derive OVERLAP_MIN_COUNT from
         timing.tuning_crossovers under the SHIPPED calibrated shaped
         link (link_tiers.outer — the hier gate's WAN-class wire) and
         this run's compute fit; FAIL unless the window opens and
         covers the workload's gradient. The stripe count is the cost
         model's argmin (asserted), never hardcoded.
      3. MEASURED (8-dev mesh, interleaved medians): the ONE-dispatch
         fused-overlapped train step vs the serial dispatch->compute
         form a register-0 caller actually runs — the eager
         three-dispatch chain whose allreduce is the rx-geometry
         segmented ring (the same flat-segmented posture the hier
         gate's twin measures; the register replaces that
         segmentation with cost-model stripes). Gate >= 2x. The
         EQUAL-PLAN eager twin (same striped plan, three dispatches)
         is asserted BITWISE-identical to the fused program and its
         measured parity is reported unvarnished, not gated: the
         memcpy-wire mesh has no wire time for overlap to hide, so at
         equal plan the one-program form only re-arranges host-side
         thunk scheduling (the moe gate's parity posture).
      4. PREDICTED (shaped link): fused-overlapped vs serial
         dispatch->compute AT THE SAME STRIPES through
         timing.predict_sequence's busy-link/busy-core pipeline,
         >= 2x — the wire the memcpy mesh doesn't have, claimed
         through the same link model every selection register rides
         (the quant/hier/moe posture).

    stdout: ONE JSON line."""
    import jax

    from accl_tpu.constants import DEFAULT_EAGER_RX_BUF_SIZE, Operation
    from accl_tpu.models import transformer as trf
    from accl_tpu.sequencer.timing import (
        best_overlap_stripes,
        predict_sequence,
        tuning_crossovers,
    )
    from accl_tpu.telemetry.feedback import default_tier_links

    world = min(len(jax.devices()), 8)
    cfg = _overlap_cfg(jax)
    rng = np.random.default_rng(17)
    tokens = rng.integers(0, cfg.vocab, (world, 1, 8)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=2)

    tiers = default_tier_links()
    if tiers is None:
        raise SystemExit(
            "FAIL: timing model carries no link_tiers — run "
            "bench.py --hier-gate first (the overlap claim is made "
            "under the calibrated shaped link)")
    link = _shipped_link()

    # 1. calibrate the compute term from telemetry spans and persist it
    fit, _trace = _overlap_compute_calibration(jax, world)
    print(f"  compute fit: alpha {fit.alpha * 1e3:.1f} ms + "
          f"{fit.rate / 1e6:.1f} MB/s of gradient", file=sys.stderr)
    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    model_path = outdir / "timing_model.json"
    model = json.loads(model_path.read_text()) if model_path.exists() \
        else {}
    model["compute_fit"] = {
        "source": f"bench.py --overlap-gate (w{world} CPU mesh, "
                  "transformer fwd+bwd at two model sizes)",
        "alpha_us": fit.alpha * 1e6,
        "grad_gbps": fit.rate / 1e9,
    }
    model_path.write_text(json.dumps(model, indent=1, sort_keys=True)
                          + "\n")

    # 2. the register from the measured crossover, under the shaped link
    cross = tuning_crossovers(link, world=world, tier_links=tiers,
                              compute_fit=fit)
    reg = int(cross["overlap_min_bytes"])
    n = trf.train_param_count(cfg)
    grad_bytes = n * 4
    print(f"  overlap crossover window: >= {reg} B "
          f"(gradient {grad_bytes} B)", file=sys.stderr)
    if not 0 < reg <= grad_bytes:
        raise SystemExit(
            f"FAIL: the calibrated overlap window ({reg} B) does not "
            f"cover the {grad_bytes} B train-step gradient; re-run "
            "bench.py --hier-gate / tools/timing_model.py if the link "
            "legitimately moved")

    overlap = _overlap_harness(jax, world, cfg, tokens, targets,
                               serial=False, overlap_reg=reg)
    twin = _overlap_harness(jax, world, cfg, tokens, targets,
                            serial=True, overlap_reg=reg)
    serial0 = _overlap_harness(jax, world, cfg, tokens, targets,
                               serial=True, overlap_reg=0)
    plans = overlap["prog"].plans
    ar_plan = plans[1]
    S = ar_plan.stripes
    olink = tiers.outer
    want_s = best_overlap_stripes(
        olink, n, 4, world, compute_s=fit.seconds(grad_bytes),
        rx_buf_bytes=DEFAULT_EAGER_RX_BUF_SIZE)
    assert S == want_s and S > 1, \
        f"stripe count {S} is not the cost model's argmin {want_s}"
    print(f"  register-selected plan: {ar_plan.algorithm.name} "
          f"S={S} (cost-model argmin)", file=sys.stderr)

    # 3. measured, bitwise first (equal-plan twin), then interleave one
    # step per side per round and take medians
    out_o = np.asarray(overlap["step"]())
    out_t = np.asarray(twin["step"]())
    np.testing.assert_array_equal(
        out_o, out_t,
        err_msg="overlapped fused != serial eager at fp32")
    np.asarray(serial0["step"]())  # warm the register-0 serial form
    rounds = 4
    t_o, t_t, t_s0 = [], [], []
    for _ in range(rounds):
        t0 = time.perf_counter()
        jax.block_until_ready(overlap["step"]())
        t_o.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        jax.block_until_ready(serial0["step"]())
        t_s0.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        jax.block_until_ready(twin["step"]())
        t_t.append(time.perf_counter() - t0)
    sec_o = float(np.median(t_o))
    sec_s0 = float(np.median(t_s0))
    sec_t = float(np.median(t_t))
    measured_x = sec_s0 / sec_o
    parity_x = sec_t / sec_o

    # 4. predicted under the shaped link: the same three descriptors,
    # fused+pipelined vs serial dispatch->compute (striped chains back
    # to back + a dispatch per stage)
    compute_s = fit.seconds(grad_bytes)
    calls = [(Operation.copy, plans[0], n, 4),
             (Operation.allreduce, ar_plan, n, 4),
             (Operation.combine, plans[2], n, 4)]
    pkw = dict(rx_buf_bytes=DEFAULT_EAGER_RX_BUF_SIZE,
               dispatch_alpha=olink.alpha, compute_s=compute_s)
    pred_olap = predict_sequence(olink, calls, world, fused=True, **pkw)
    pred_serial = predict_sequence(olink, calls, world, fused=False,
                                   **pkw)
    pred_x = pred_serial / max(pred_olap, 1e-12)
    print(f"  overlap train step w{world}: fused {sec_o * 1e3:.1f} ms "
          f"vs register-0 serial {sec_s0 * 1e3:.0f} ms "
          f"({measured_x:.1f}x measured) vs equal-plan eager "
          f"{sec_t * 1e3:.1f} ms ({parity_x:.2f}x, memcpy-wire mesh); "
          f"shaped-link predicted {pred_serial * 1e3:.0f} -> "
          f"{pred_olap * 1e3:.0f} ms ({pred_x:.2f}x)", file=sys.stderr)
    print(json.dumps({
        "metric": "train_step overlap: fused stripe-overlapped vs "
                  f"serial dispatch->compute (w{world} CPU mesh)",
        "value": round(measured_x, 2),
        "unit": "x",
        "platform": "cpu-fallback",
        "stripes": S,
        "overlap_min_bytes": reg,
        "grad_bytes": grad_bytes,
        "predicted_x_shaped_link": round(pred_x, 2),
        "measured_equal_plan_x": round(parity_x, 3),
        "compute_fit": model["compute_fit"],
        "fused_s": sec_o,
        "serial_register0_s": sec_s0,
        "serial_equal_plan_s": sec_t,
    }))
    fails = []
    if measured_x < 2.0:
        fails.append(
            f"fused-overlapped measured {measured_x:.2f}x < 2x the "
            "serial dispatch->compute form (register 0)")
    if pred_x < 2.0:
        fails.append(
            f"shaped-link prediction {pred_x:.2f}x < 2x serial at "
            "equal stripes")
    for f in fails:
        print(f"FAIL: {f}", file=sys.stderr)
    if fails:
        sys.exit(1)


def _moe_gate_main():
    """bench.py --moe-gate: the fused expert-parallel dispatch gate
    (ROADMAP item 4). FAILs unless (a) the fused+quantized
    dispatch->expert->combine program ships <= 1/2 the eager fp32
    baseline's traced ppermute wire bytes, (b) the ONE-dispatch fused
    program wins the measured median against the descriptor-per-stage
    eager form at the same wire, and (c) fused+int8 beats eager fp32
    >= 2x under the shipped calibrated link (the wire the CPU mesh
    doesn't have); fp32 fused-vs-eager bitwise identity is asserted
    inside the lane and the measured fp32 parity ratio is reported
    unvarnished. One JSON line."""
    import jax

    world = min(len(jax.devices()), 8)
    r = bench_moe_dispatch(jax, world)
    print(json.dumps({
        "metric": "moe_dispatch: fused+int8 layer step vs eager "
                  f"(w{world} CPU mesh)",
        "value": round(r["fusion_x"], 2),
        "unit": "x",
        "platform": "cpu-fallback",
        "wire_reduction_x": round(r["wire_ratio"], 2),
        "predicted_vs_eager_fp32_x": round(r["pred_x"], 2),
        "measured_vs_eager_fp32_x": round(r["parity_x"], 2),
        "quantized_max_rel_error": round(r["max_rel"], 6),
    }))
    fails = []
    if r["wire_ratio"] < 2.0:
        fails.append(
            f"traced wire-byte reduction {r['wire_ratio']:.2f}x < 2x")
    if r["fusion_x"] < 1.0:
        fails.append(
            f"fused measured {r['fusion_x']:.2f}x < 1x the "
            "descriptor-per-stage eager form at equal wire")
    if r["pred_x"] < 2.0:
        fails.append(
            f"calibrated-link prediction {r['pred_x']:.2f}x < 2x "
            "eager fp32")
    for f in fails:
        print(f"FAIL: {f}", file=sys.stderr)
    if fails:
        sys.exit(1)


def _quant_gate_main():
    """bench.py --quant-gate: ONLY the quantized-allreduce gate lane
    (for the CI lint job, which wants the wire-byte gate without paying
    the tier1-smoke job's full sequence benchmark twice). One JSON line;
    exit 1 when the 16 MiB wire-byte reduction drops below 1.9x."""
    import jax

    world = min(len(jax.devices()), 4)
    reduction, max_rel = bench_quantized_wire(jax, world)
    print(json.dumps({
        "metric": "quantized allreduce ppermute bytes-on-wire reduction "
                  f"vs fp32 at 16 MiB (w{world})",
        "value": round(reduction, 2),
        "unit": "x",
        "vs_baseline": round(reduction / 4.0, 3),  # 4x = scale-free ideal
        "quantized_max_rel_error": round(max_rel, 6),
    }))
    if reduction < 1.9:
        print(f"FAIL: quantized allreduce wire reduction "
              f"{reduction:.2f}x < 1.9x at 16 MiB", file=sys.stderr)
        sys.exit(1)


def measure_telemetry_overhead(n=50_000):
    """Per-site cost of the DISABLED tracing path (the predicate +
    no-op span the facade pays on every call when ACCL_TELEMETRY is
    off). The smoke gate multiplies this by the spans-per-chain count
    and requires the product under 1% of the measured fused-chain time:
    instrumentation must be free when nobody is watching. The always-on
    observability layer (metrics registry + flight recorder) counts as
    'somebody watching' — it is detached for the measurement and its
    OWN traced-hot-path budget is gated separately (< 3%, bench.py
    --obs-gate)."""
    import accl_tpu.telemetry as telemetry

    tr = telemetry.get_tracer()
    was = tr.enabled
    was_obs = telemetry.observability_enabled()
    tr.disable()
    telemetry.disable_observability()
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("overhead_probe", cat="call", track="facade"):
                pass
        return (time.perf_counter() - t0) / n
    finally:
        if was:
            tr.enable()
        if was_obs:
            telemetry.enable_observability()


# ~span sites per smoke chain: facade call + sequence + four phases +
# headroom. ONE constant and ONE budget shared by the --smoke and
# --trace gates, so retuning either cannot desynchronize them.
TELEMETRY_SPAN_SITES = 8
TELEMETRY_OVERHEAD_BUDGET = 0.01


def telemetry_disabled_gate(sec_fused):
    """(per_site_seconds, ratio, ok) for the disabled-instrumentation
    budget: TELEMETRY_SPAN_SITES no-op spans must cost under
    TELEMETRY_OVERHEAD_BUDGET of the measured fused chain."""
    per_site = measure_telemetry_overhead()
    ratio = TELEMETRY_SPAN_SITES * per_site / max(sec_fused, 1e-9)
    return per_site, ratio, ratio < TELEMETRY_OVERHEAD_BUDGET


def _trace_sweep_native(world=8, sizes=(64 * 1024, 1024 * 1024), iters=2):
    """The measured-hop source for bench.py --trace: a native EmuWorld
    sweep with the device-resident trace ring armed (ACCL_RT_TRACE=1),
    drained into SPAN v1 events with one track per rank and every span
    carrying its timing.predict estimate + aggregate cost coefficients
    (telemetry.native). Returns (events, dropped)."""
    from accl_tpu import ReduceFunction
    from accl_tpu.device.emu_device import EmuWorld
    from accl_tpu.telemetry import default_link
    from accl_tpu.telemetry import native as tnative

    saved = os.environ.get("ACCL_RT_TRACE")
    os.environ["ACCL_RT_TRACE"] = "1"
    try:
        w = EmuWorld(world, max_eager=tnative.DEFAULT_MAX_EAGER,
                     rx_buf_bytes=tnative.DEFAULT_RX_BUF)
    finally:
        if saved is None:
            os.environ.pop("ACCL_RT_TRACE", None)
        else:
            os.environ["ACCL_RT_TRACE"] = saved
    try:
        def body(rank, i):
            for nbytes in sizes:
                count = nbytes // 4
                x = np.ones(count, np.float32)
                out = np.zeros(count, np.float32)
                ag = np.zeros(count * world, np.float32)
                for _ in range(iters):
                    rank.allreduce(x, out, count, ReduceFunction.SUM)
                    rank.bcast(x, count, root=0)
                    rank.allgather(x, ag, count)

        w.run(body)
        return tnative.drain_world(w, link=default_link())
    finally:
        w.close()


def _trace_main():
    """bench.py --trace: the telemetry lane. Emits

      - accl_log/trace.json        (SPAN v1 trace document)
      - accl_log/trace_chrome.json (Chrome trace-event JSON, one track
                                    per rank/executor, Perfetto-loadable)

    from (a) the facade + fused-sequence chain on the CPU mesh (host
    spans: every collective call, the record/lint/compile/dispatch
    phases, per-step predicted times) and (b) a native 8-rank emulator
    sweep with the device trace ring armed (per-rank measured spans).
    The JSON line carries the residual section: median
    |predicted-measured|/measured under the shipped default link vs the
    calibrate_from_trace() refit — the refit must not be worse, or the
    feedback loop is broken. Also gates the DISABLED instrumentation
    cost (<1% of the fused chain)."""
    import jax

    from accl_tpu import telemetry

    tr = telemetry.get_tracer()
    tr.enable()
    world = min(len(jax.devices()), 8)

    # host lane: every collective + a fused sequence, spans into the ring
    rows, _ = bench_sequence(jax, world)
    sec_fused = next(s for t, b, s, *_ in rows if "fused" in t)

    # native lane: per-rank measured spans (one track per rank)
    native_events, native_dropped = _trace_sweep_native(world=world)
    tr.extend(native_events)

    trace = tr.to_trace({
        "world": world,
        "native_dropped": native_dropped,
        "cost_shape": "aggregate",
    })
    from accl_tpu.telemetry import (residual_report, to_chrome,
                                    validate_trace, write_trace)

    validate_trace(trace)
    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    write_trace(outdir / "trace.json", trace)
    write_trace(outdir / "trace_chrome.json", to_chrome(trace))
    report = residual_report(trace)

    per_site, overhead_ratio, overhead_ok = telemetry_disabled_gate(
        sec_fused)
    tracks = sorted({sp["track"] for sp in trace["spans"]})
    sr_med = report["span_residuals"]["median_rel_err"]
    print(f"  trace: {len(trace['spans'])} spans on {len(tracks)} tracks "
          f"({', '.join(tracks)}); span residual median "
          f"{'n/a' if sr_med is None else f'{sr_med:.3f}'}; disabled "
          f"overhead {per_site * 1e9:.0f} ns/site "
          f"({overhead_ratio * 100:.4f}% of fused chain)", file=sys.stderr)
    cal = report.get("calibration", {})
    # None-safe readout: a checkout without accl_log/timing_model.json
    # has no default link — the JSON stays valid (null, never NaN) and
    # the gate below says WHY it failed instead of raising
    refit_err = cal.get("median_rel_err_refit")
    default_err = cal.get("median_rel_err_default")
    print(json.dumps({
        "metric": "telemetry trace residuals: median |pred-meas|/meas, "
                  f"shipped default link -> calibrate_from_trace refit "
                  f"(w{world} native sweep)",
        "value": round(refit_err, 4) if refit_err is not None else None,
        "unit": "rel_err",
        "vs_baseline": (round(refit_err / default_err, 4)
                        if refit_err is not None and default_err
                        else None),
        "residuals": report,
        "spans": len(trace["spans"]),
        "tracks": len(tracks),
        "native_dropped": native_dropped,
        "telemetry_disabled_overhead_pct": round(overhead_ratio * 100, 4),
    }))
    if "error" in cal:
        print(f"FAIL: no calibratable spans: {cal['error']}",
              file=sys.stderr)
        sys.exit(1)
    if default_err is None:
        print("FAIL: no shipped timing model to compare against "
              "(accl_log/timing_model.json missing or unreadable) — the "
              "residual gate needs the default link", file=sys.stderr)
        sys.exit(1)
    if not cal.get("improved", False):
        print("FAIL: calibrate_from_trace refit did not reduce the "
              f"median residual (refit {refit_err:.3f} "
              f"vs default {default_err:.3f})", file=sys.stderr)
        sys.exit(1)
    if not overhead_ok:
        print(f"FAIL: disabled tracing costs {overhead_ratio * 100:.2f}% "
              "of the fused chain (>= "
              f"{TELEMETRY_OVERHEAD_BUDGET * 100:.0f}% budget)",
              file=sys.stderr)
        sys.exit(1)


# the observability-gate contract (bench.py --obs-gate), recorded in
# BASELINE_BENCH.json's "observability" block so a config drift is a
# baseline diff, not a silent retune: the metrics observe path must
# cost < OBS_OVERHEAD_BUDGET of the per-call median latency on the
# traced hot path, and the drift sentinel (window/min_samples below)
# must flag an injected WAN regime change within one window while
# reporting zero false positives on the stable control run.
OBS_OVERHEAD_BUDGET = 0.03
OBS_SENTINEL_WINDOW = 24
# reference armed over HALF the reference sweep (not the library
# default): a reference median taken over 12 spans absorbs the
# between-sweep jitter a throttled CI host shows, and the raised band
# floor keeps ordinary scheduler noise (< ~1.35x) out of the verdict —
# this gate injects an ~8x regime change, the floor costs no detection
OBS_SENTINEL_MIN_SAMPLES = 12
OBS_SENTINEL_BAND_FLOOR = 0.35
OBS_SPANS_PER_CALL = 2  # facade call span + native span, conservative


def _obs_sweep(world_obj, sizes, iters):
    """Lockstep allreduce sweep on a native EmuWorld: the traced
    workload every --obs-gate leg measures."""
    from accl_tpu import ReduceFunction

    def body(rank, _i):
        for nbytes in sizes:
            n = nbytes // 4
            x = np.ones(n, np.float32)
            out = np.zeros(n, np.float32)
            for _ in range(iters):
                rank.allreduce(x, out, n, ReduceFunction.SUM)

    world_obj.run(body)


def _obs_drain_events(world_obj, link):
    """Drain the world's trace rings into SPAN v1 events (predictions
    under `link`), time-ordered — the replay order the sentinel sees."""
    from accl_tpu.telemetry import native as tnative

    events, _ = tnative.drain_world(world_obj, link=link)
    return sorted(events, key=lambda ev: ev["ts_ns"])


def _obs_gate_main():
    """bench.py --obs-gate: the always-on observability layer's two
    measured claims, CI-gated (ISSUE 13 acceptance):

      1. DRIFT SENTINEL on an injected WAN-shaper regime change: bring
         up a shaped 4-rank native TCP world (regime A), calibrate
         LinkParams from its own warmup spans, arm the sentinel on a
         reference sweep (residuals of regime-A measurements vs
         regime-A predictions), then run a CONTROL sweep in the same
         regime — the sentinel must report ZERO false positives — and
         finally re-create the world ~8x slower (regime B: the WAN
         shaper emulates congestion/throttle/interference) while the
         predictions stay on the STALE regime-A link: the sentinel
         must flag the op within one window, and the gate reports the
         detection latency in dispatches plus the per-rank straggler
         attribution.

      2. METRICS OVERHEAD on the traced hot path: the per-event cost
         of the span->metrics observe rule (measured over a large
         replay of a real drained event), times OBS_SPANS_PER_CALL,
         must stay under OBS_OVERHEAD_BUDGET (3%) of the per-call
         MEDIAN latency measured in the control sweep.

    stdout: ONE JSON line {metric, value = detection latency in
    dispatches, false_positives, overhead_pct, straggler report}."""
    from accl_tpu.telemetry import calibrate_from_trace
    from accl_tpu.telemetry import native as tnative
    from accl_tpu.telemetry.metrics import (
        DriftSentinel,
        MetricsObserver,
        MetricsRegistry,
    )
    from accl_tpu.telemetry.tracer import SCHEMA_VERSION
    from accl_tpu.device.emu_device import EmuWorld

    world = 4
    # ONE rendezvous-class size: each ring chunk is one jumbo frame, so
    # the shaper's per-frame charge dominates the host's intrinsic
    # per-segment cost (the hier gate's lesson — shaping far above
    # scheduler noise measures the link, not scheduler luck), and every
    # span in the window shifts by the same regime ratio
    sizes = (128 * 1024,)
    iters = 6
    # regime A: a DCN-class shaped wire (per-frame alpha + bytes/beta,
    # native frame_out); regime B: ~8x slower per frame (~4x wall-clock
    # after the host's intrinsic per-segment cost) — the mid-run
    # congestion/throttle event the sentinel exists to catch, injected
    # far above host jitter so the gate measures detection, not luck
    regime_a = {"ACCL_RT_WAN_ALPHA_US": "500", "ACCL_RT_WAN_GBPS": "1.0"}
    regime_b = {"ACCL_RT_WAN_ALPHA_US": "4000",
                "ACCL_RT_WAN_GBPS": "0.0625"}
    saved = {k: os.environ.get(k) for k in
             ("ACCL_RT_TRACE", "ACCL_RT_WAN_ALPHA_US", "ACCL_RT_WAN_GBPS")}
    os.environ["ACCL_RT_TRACE"] = "1"
    wkw = dict(max_eager=tnative.DEFAULT_MAX_EAGER,
               rx_buf_bytes=tnative.DEFAULT_RX_BUF)

    def _mkworld(regime):
        os.environ.update(regime)
        return EmuWorld(world, transport="tcp", **wkw)

    try:
        wa = _mkworld(regime_a)
        try:
            # 0. throwaway warm sweep: the FIRST sweep on a fresh world
            # pays TCP session establishment and cold buffer pools, and
            # calibrating on it would bias every later residual
            _obs_sweep(wa, sizes, 2)
            for r in wa.ranks:
                r.trace_read()
            # 1. calibrate the link from regime-A warmup spans — the
            # "shipped" model of the current regime
            _obs_sweep(wa, sizes, iters)
            warm = _obs_drain_events(wa, link=None)
            link = calibrate_from_trace(
                {"schema": SCHEMA_VERSION, "spans": warm})
            print(f"  regime-A link: alpha {link.alpha * 1e6:.0f} us "
                  f"beta {link.beta / 1e9:.3f} GB/s "
                  f"({len(warm)} warmup spans)", file=sys.stderr)

            # 2. arm the sentinel on a reference sweep, then prove the
            # control sweep (same regime) stays quiet
            obs = MetricsObserver(
                MetricsRegistry(),
                DriftSentinel(window=OBS_SENTINEL_WINDOW,
                              min_samples=OBS_SENTINEL_MIN_SAMPLES,
                              band_floor=OBS_SENTINEL_BAND_FLOOR))
            _obs_sweep(wa, sizes, iters)
            for ev in _obs_drain_events(wa, link):
                obs(ev)
            armed = {op: row for op, row in obs.sentinel.verdict().items()
                     if row.get("armed")}
            _obs_sweep(wa, sizes, iters)
            control_events = _obs_drain_events(wa, link)
            for ev in control_events:
                obs(ev)
            false_pos = obs.sentinel.flagged()
            ctrl = obs.sentinel.verdict().get("allreduce", {})
            print(f"  control: {len(control_events)} spans, median "
                  f"residual {ctrl.get('median_rel_err', float('nan')):.3f}"
                  f" vs band <= {ctrl.get('band_hi', float('nan')):.3f}, "
                  f"flagged={false_pos}", file=sys.stderr)
        finally:
            wa.close()

        # 3. regime change: same workload, same STALE link for the
        # predictions, 8x slower wire — feed span by span and count
        # dispatches until the band-leave verdict fires
        wb = _mkworld(regime_b)
        try:
            _obs_sweep(wb, sizes, iters)
            shift_events = _obs_drain_events(wb, link)
        finally:
            wb.close()
        detect_at = None
        for i, ev in enumerate(shift_events):
            obs(ev)
            if "allreduce" in obs.sentinel.flagged():
                detect_at = i + 1
                break
        drift = obs.sentinel.verdict().get("allreduce", {})
        stragglers = obs.sentinel.straggler_report()
        print(f"  regime change: flagged after "
              f"{detect_at if detect_at else '>' + str(len(shift_events))}"
              f" of {len(shift_events)} spans (window "
              f"{OBS_SENTINEL_WINDOW}); rolling median residual "
              f"{drift.get('median_rel_err', float('nan')):.3f} vs band "
              f"<= {drift.get('band_hi', float('nan')):.3f}",
              file=sys.stderr)

        # 4. metrics overhead on the traced hot path: per-event observe
        # cost (replaying a REAL drained event) vs per-call median
        per_call = float(np.median(
            [ev["args"]["measured_s"] for ev in control_events]))
        probe = control_events[0]
        reps = 20_000
        t0 = time.perf_counter()
        for _ in range(reps):
            obs(probe)
        per_event = (time.perf_counter() - t0) / reps
        overhead = OBS_SPANS_PER_CALL * per_event / max(per_call, 1e-9)
        print(f"  metrics overhead: {per_event * 1e9:.0f} ns/event x "
              f"{OBS_SPANS_PER_CALL} spans/call = "
              f"{overhead * 100:.3f}% of per-call median "
              f"{per_call * 1e3:.2f} ms (budget "
              f"{OBS_OVERHEAD_BUDGET * 100:.0f}%)", file=sys.stderr)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    print(json.dumps({
        "metric": "observability gate: drift-sentinel detection latency "
                  f"under an injected WAN regime change (w{world} native "
                  "TCP, ~8x link slowdown, stale-link predictions)",
        "value": detect_at,
        "unit": "dispatch spans",
        "platform": "cpu-emulator",
        "window": OBS_SENTINEL_WINDOW,
        "min_samples": OBS_SENTINEL_MIN_SAMPLES,
        "false_positives": len(false_pos),
        "control_median_rel_err": ctrl.get("median_rel_err"),
        "drift_median_rel_err": drift.get("median_rel_err"),
        "band_hi": drift.get("band_hi"),
        "metrics_overhead_pct": round(overhead * 100, 3),
        "metrics_overhead_budget_pct": OBS_OVERHEAD_BUDGET * 100,
        "per_call_median_s": per_call,
        "stragglers": stragglers,
    }))
    if not armed:
        print("FAIL: sentinel never armed a reference on the reference "
              "sweep — too few predicted spans", file=sys.stderr)
        sys.exit(1)
    if false_pos:
        print(f"FAIL: sentinel flagged {false_pos} on the STABLE control "
              "run — false positives would make every drift report "
              "untrustworthy", file=sys.stderr)
        sys.exit(1)
    if detect_at is None:
        print("FAIL: sentinel did not flag the injected regime change "
              f"within {len(shift_events)} dispatches — the band-leave "
              "verdict missed a ~8x link slowdown", file=sys.stderr)
        sys.exit(1)
    if detect_at > OBS_SENTINEL_WINDOW:
        print(f"FAIL: detection latency {detect_at} dispatches exceeds "
              f"the sentinel window ({OBS_SENTINEL_WINDOW})",
              file=sys.stderr)
        sys.exit(1)
    if overhead >= OBS_OVERHEAD_BUDGET:
        print(f"FAIL: metrics observe path costs {overhead * 100:.2f}% "
              "of per-call median latency (budget "
              f"{OBS_OVERHEAD_BUDGET * 100:.0f}%)", file=sys.stderr)
        sys.exit(1)


# the fault-gate contract (bench.py --fault-gate): a mid-stream rank
# death on the native world must be detected through MODEL-DERIVED
# deadlines (never a fixed timeout), survived within the bounded
# retry+reconfigure budget with ZERO wrong answers (every recovery plan
# re-certified through semantics + modelcheck before install; every
# completed dispatch bitwise vs its oracle), and the ARMED deadline
# seam's measured per-dispatch bookkeeping must cost <
# FAULT_OVERHEAD_BUDGET of the per-dispatch median on the no-fault
# control (the obs-gate's per-event-cost methodology), with zero false
# misses and bitwise answers; the A/B wall delta is reported alongside.
FAULT_GATE_WORLD = 4
# 256 KiB fp32 per rank: dispatches run in the ms regime where a
# deadline is a meaningful per-call bound, and the guard's per-wait
# bookkeeping (one cached lookup + a perf_counter pair) sits far under
# the 3% control budget instead of fighting scheduler noise at the
# latency floor
FAULT_GATE_COUNT = 65536
FAULT_OVERHEAD_BUDGET = 0.03
FAULT_RETRY_BUDGET = 1  # transient-straggler retries before exclusion
FAULT_CONTROL_ROUNDS = 16
FAULT_HEALTHY_DISPATCHES = 3  # completed pre-kill (the env lever's N)
FAULT_RECOVERY_ROUNDS = 6


def _fault_dispatch_round(world_obj, xs, count, guard=None, comm_addr=0,
                          skip=(), iters=1):
    """`iters` lockstep allreduce dispatches across the world: every
    rank starts, then completes through the armed guard (deadline-
    bounded) or a plain wait. Returns (wall_s_per_dispatch, last
    results|None per rank); a rank in `skip` does nothing (the dead
    rank after exclusion). iters > 1 amortizes the per-round thread
    spawn out of the per-dispatch number (the overhead-gate
    measurement must compare WAIT paths, not harness noise)."""
    from accl_tpu import ReduceFunction
    from accl_tpu.constants import Operation
    from accl_tpu.descriptor import CallOptions

    def body(rank, i):
        if i in skip:
            return None
        out = np.zeros(count, np.float32)
        for _k in range(iters):
            h = rank.start(CallOptions(
                scenario=Operation.allreduce, count=count,
                function=int(ReduceFunction.SUM), data_type=3,
                comm_addr=comm_addr), op0=xs[i].copy(), res=out)
            if guard is not None:
                guard.wait(rank, h, "allreduce", count)
            else:
                rank.wait(h)
        return out

    t0 = time.perf_counter()
    results = world_obj.run(body)
    return (time.perf_counter() - t0) / iters, results


def _fault_gate_main():
    """bench.py --fault-gate: the self-healing loop's measured claims
    (ISSUE 14 acceptance), CI-gated:

      1. NO-FAULT CONTROL with armed deadlines: interleaved lockstep
         allreduce rounds on the 4-rank native TCP world, plain waits
         vs NativeDeadlineGuard waits (deadlines derived from THIS
         world's calibrated link + its measured residual band) — zero
         false misses, every answer bitwise vs the oracle, and the
         armed seam's measured per-wait bookkeeping under 3% of the
         per-dispatch median (the A/B wall delta is reported
         unvarnished but not gated: µs-scale bookkeeping under
         ms-scale dispatches on a throttled host measures scheduler
         luck, not the code path — the obs gate's methodology).

      2. SOAK WITH INJECTED RANK DEATH: a fresh world armed with
         ACCL_RT_FAULT_KILL_RANK kills the victim mid-stream after
         FAULT_HEALTHY_DISPATCHES completed calls. Survivors must
         detect through derived deadlines within the bounded
         retry budget (every wedged attempt costs one deadline, never
         a fixed timeout), attribute the suspect by silence, exclude,
         re-plan over the survivor world and RE-CERTIFY through the
         existing semantics + modelcheck stack (an uncertified plan is
         never installed), fence the stale channel state
         (accl_rt_flush_rx), and produce post-recovery answers on the
         survivor communicator that match the numpy oracle over
         survivors BITWISE.

      3. CERTIFIED DEGRADED MODE on the XLA mesh: allreduce(mode=
         "live_subset") over the same survivor set matches the
         survivor oracle bitwise and its lifted schedule certifies
         clean against the declared-survivor spec (zero wrong answers
         is certifier-enforced, not asserted).

      4. FLAT-VS-RECONFIGURED CROSSOVER: staying on the dead world
         pays one derived deadline per dispatch forever; the measured
         reconfiguration cost amortizes after
         ceil(reconfig_s / (deadline_s - t_recovered_s)) dispatches —
         gated finite (a recovered dispatch must beat the deadline).

    stdout: ONE JSON line {metric, value = recovery wall seconds, ...}."""
    import jax

    from accl_tpu import ReduceFunction
    from accl_tpu.constants import ACCLError, Operation
    from accl_tpu.descriptor import CallOptions
    from accl_tpu.device.emu_device import EmuWorld
    from accl_tpu.resilience import (
        DeadlineMissedError,
        DeadlinePolicy,
        NativeDeadlineGuard,
        ResilienceManager,
        RetryBudget,
    )
    from accl_tpu.telemetry import calibrate_from_trace
    from accl_tpu.telemetry import native as tnative
    from accl_tpu.telemetry import recorder as flight
    from accl_tpu.telemetry.tracer import SCHEMA_VERSION

    world = FAULT_GATE_WORLD
    count = FAULT_GATE_COUNT
    victim = world - 2  # an interior rank: both ring neighbors survive
    rng = np.random.default_rng(14)
    xs = rng.integers(-32, 32, size=(world, count)).astype(np.float32)
    oracle = xs.sum(0)
    saved = {k: os.environ.get(k) for k in
             ("ACCL_RT_TRACE", "ACCL_RT_FAULT_KILL_RANK",
              "ACCL_RT_FAULT_KILL_AFTER")}
    os.environ["ACCL_RT_TRACE"] = "1"
    os.environ.pop("ACCL_RT_FAULT_KILL_RANK", None)
    os.environ.pop("ACCL_RT_FAULT_KILL_AFTER", None)
    wkw = dict(max_eager=tnative.DEFAULT_MAX_EAGER,
               rx_buf_bytes=tnative.DEFAULT_RX_BUF)
    try:
        # -- calibrate: the link AND its honest residual band from THIS
        # world's warm spans (the deadline is derived end to end)
        wa = EmuWorld(world, transport="tcp", **wkw)
        try:
            _obs_sweep(wa, (count * 4,), 2)  # cold TCP sessions
            for r in wa.ranks:
                r.trace_read()
            _obs_sweep(wa, (count * 4,), 6)
            warm = _obs_drain_events(wa, link=None)
            link = calibrate_from_trace(
                {"schema": SCHEMA_VERSION, "spans": warm})
            _obs_sweep(wa, (count * 4,), 6)
            ref_events = _obs_drain_events(wa, link)
            residuals = [
                abs(ev["args"]["predicted_s"] - ev["args"]["measured_s"])
                / ev["args"]["measured_s"]
                for ev in ref_events
                if ev["args"].get("predicted_s")
                and ev["args"].get("measured_s", 0) > 0]
            policy = DeadlinePolicy(link, world=world,
                                    rx_buf_bytes=tnative.DEFAULT_RX_BUF,
                                    max_eager_size=tnative.DEFAULT_MAX_EAGER)
            ref = policy.arm_from_residuals("allreduce", residuals)
            deadline_s = policy.deadline_s("allreduce", count)
            print(f"  link: alpha {link.alpha * 1e6:.0f} us, beta "
                  f"{link.beta / 1e9:.2f} GB/s; residual ref "
                  f"{ref:.3f} over {len(residuals)} spans -> deadline "
                  f"{deadline_s * 1e3:.1f} ms (predicted "
                  f"{policy.predict_s('allreduce', count) * 1e3:.1f} ms)",
                  file=sys.stderr)

            # -- leg 1: armed vs unarmed control, interleaved ---------
            # the control guard reports into its own manager, so the
            # zero-false-misses claim below is a MEASUREMENT (a late
            # success records a verdict there), not a fresh counter
            mgr_probe = ResilienceManager(world, policy=policy)
            guard = NativeDeadlineGuard(policy, manager=mgr_probe)
            for r in wa.ranks:
                guard.arm(r, "allreduce", count)
            t_plain, t_armed = [], []
            for _ in range(FAULT_CONTROL_ROUNDS):
                s, res = _fault_dispatch_round(wa, xs, count, iters=8)
                t_plain.append(s)
                for out in res:
                    assert np.array_equal(out, oracle), \
                        "control (plain) answer wrong"
                s, res = _fault_dispatch_round(wa, xs, count,
                                               guard=guard, iters=8)
                t_armed.append(s)
                for out in res:
                    assert np.array_equal(out, oracle), \
                        "control (armed) answer wrong"
            # The GATE measures the armed seam's deterministic per-wait
            # bookkeeping (one cached policy lookup + a perf_counter
            # pair + the deadline comparison) against the per-dispatch
            # median — the obs-gate's methodology for a cost that is
            # µs-scale under ms-scale dispatches: the A/B wall delta on
            # a throttled CI host is scheduler noise either way (it
            # measures the machine, not the code path) and is REPORTED
            # unvarnished below, not gated.
            reps = 20_000
            t0 = time.perf_counter()
            for _ in range(reps):
                _p, _dl = policy.predict_and_deadline("allreduce", count)
                _s = time.perf_counter()
                _ok = (time.perf_counter() - _s) <= _dl
            seam_s = (time.perf_counter() - t0) / reps
            per_dispatch = float(np.median(t_plain))
            overhead = seam_s / max(per_dispatch, 1e-9)
            wall_delta = (float(np.median(t_armed))
                          / max(per_dispatch, 1e-9)) - 1.0
            print(f"  control: armed seam {seam_s * 1e9:.0f} ns/dispatch"
                  f" = {overhead * 100:.3f}% of the "
                  f"{per_dispatch * 1e3:.2f} ms/dispatch median; A/B "
                  f"wall delta {wall_delta * 100:+.2f}% over "
                  f"{FAULT_CONTROL_ROUNDS} interleaved rounds "
                  f"(reported, not gated — host noise); "
                  f"{len(mgr_probe.misses)} misses", file=sys.stderr)
        finally:
            wa.close()

        # -- leg 2: the soak with an injected mid-stream death --------
        os.environ["ACCL_RT_FAULT_KILL_RANK"] = str(victim)
        os.environ["ACCL_RT_FAULT_KILL_AFTER"] = str(
            FAULT_HEALTHY_DISPATCHES)
        wb = EmuWorld(world, transport="tcp", **wkw)
        os.environ.pop("ACCL_RT_FAULT_KILL_RANK", None)
        os.environ.pop("ACCL_RT_FAULT_KILL_AFTER", None)
        try:
            budget = RetryBudget(max_retries=FAULT_RETRY_BUDGET,
                                 backoff_base_s=0.02)
            mgr = ResilienceManager(world, policy=policy, budget=budget)
            guard = NativeDeadlineGuard(policy)
            for r in wb.ranks:
                guard.arm(r, "allreduce", count)
            for _k in range(FAULT_HEALTHY_DISPATCHES):
                _s, res = _fault_dispatch_round(wb, xs, count,
                                                guard=guard)
                for out in res:
                    assert np.array_equal(out, oracle), \
                        "pre-kill answer wrong"
            assert not mgr.misses

            # the victim's next call dies mid-stream (the env lever);
            # survivors wedge and must detect within the retry budget,
            # each attempt one lockstep phase (threads joined so the
            # stale-frame window stays inside peers' live calls)
            t_kill = time.perf_counter()
            attempts = 0
            action = None
            while action != "exclude":
                def attempt(rank, i):
                    if i == victim:
                        if attempts == 0:
                            try:  # the dying call itself
                                out = np.zeros(count, np.float32)
                                rank.allreduce(xs[i].copy(), out, count,
                                               ReduceFunction.SUM)
                            except ACCLError:
                                pass
                        return None
                    out = np.zeros(count, np.float32)
                    h = rank.start(CallOptions(
                        scenario=Operation.allreduce, count=count,
                        function=int(ReduceFunction.SUM), data_type=3),
                        op0=xs[i].copy(), res=out)
                    try:
                        guard.wait(rank, h, "allreduce", count)
                        return ("ok", out)
                    except DeadlineMissedError as e:
                        return ("miss", e.miss)

                verdicts = wb.run(attempt)
                reporters = [i for i, v in enumerate(verdicts)
                             if v is not None and v[0] == "miss"]
                if sorted(reporters) != sorted(
                        r for r in range(world) if r != victim):
                    print(f"FAIL: attempt {attempts}: survivors "
                          f"{reporters} missed, expected all of "
                          f"{[r for r in range(world) if r != victim]}",
                          file=sys.stderr)
                    sys.exit(1)
                suspect = mgr.attribute_silent(reporters)
                assert suspect == victim, \
                    f"attribution named {suspect}, victim is {victim}"
                import dataclasses as _dc

                rep = _dc.replace(verdicts[reporters[0]][1],
                                  suspect_rank=suspect,
                                  attribution="silent")
                action = mgr.record_miss(rep)
                attempts += 1
                if action == "retry":
                    time.sleep(mgr.retry_delay_s(suspect))
            detect_s = time.perf_counter() - t_kill
            # bounded-time detection: each attempt pays ONE derived
            # deadline (+ the guard's slack + backoff), never a fixed
            # constant — the budget is a function of the model
            detect_budget = attempts * (
                deadline_s * NativeDeadlineGuard.HOST_WAIT_SLACK
                + budget.delay_s(attempts) + 1.0)
            print(f"  death detected in {attempts} attempts / "
                  f"{detect_s:.2f} s (budget {detect_budget:.2f} s); "
                  f"suspect r{victim} by silence; "
                  f"{len(mgr.misses)} verdicts, post-mortem "
                  f"{'present' if flight.last_error_trace() else 'MISSING'}",
                  file=sys.stderr)

            survivors = mgr.exclude(victim)
            t_replan0 = time.perf_counter()
            rplan = mgr.replan(Operation.allreduce, count=count)
            mgr.install(rplan)
            for g in survivors:
                wb.ranks[g].flush_rx()  # the reconfiguration fence
            replan_s = time.perf_counter() - t_replan0
            assert rplan.certificate["diagnostics"] == 0

            # survivor communicator + post-recovery soak, bitwise
            from accl_tpu.communicator import Communicator, Rank
            from accl_tpu.device.base import CCLOAddr

            addr = int(CCLOAddr.DYNAMIC_BASE)
            comm = Communicator(
                [Rank(device_index=g, session_id=g) for g in survivors],
                0, addr)
            surv_oracle = xs[list(survivors)].sum(0)
            t_comm0 = time.perf_counter()
            for g in survivors:
                wb.ranks[g].write_communicator(comm)
                guard.arm(wb.ranks[g], "allreduce", count)
            comm_s = time.perf_counter() - t_comm0
            t_rec = []
            for _k in range(FAULT_RECOVERY_ROUNDS):
                s, res = _fault_dispatch_round(
                    wb, xs, count, guard=guard, comm_addr=addr,
                    skip=(victim,))
                t_rec.append(s)
                for i, out in enumerate(res):
                    if i == victim:
                        continue
                    if not np.array_equal(out, surv_oracle):
                        print(f"FAIL: post-recovery answer wrong on "
                              f"r{i}", file=sys.stderr)
                        sys.exit(1)
            t_rec_med = float(np.median(t_rec))
            recovery_s = detect_s + replan_s + comm_s + t_rec[0]
            print(f"  recovery: replan+certify+install+fence "
                  f"{replan_s:.2f} s ({rplan.source}"
                  f"{' ' + rplan.synth_key if rplan.synth_key else ''}),"
                  f" comm setup {comm_s * 1e3:.1f} ms, first good "
                  f"dispatch {t_rec[0] * 1e3:.1f} ms -> total "
                  f"{recovery_s:.2f} s; steady post-recovery "
                  f"{t_rec_med * 1e3:.2f} ms/dispatch", file=sys.stderr)
        finally:
            wb.close()

        # -- leg 3: certified degraded mode on the XLA mesh -----------
        from jax.sharding import Mesh

        from accl_tpu import ACCL
        from accl_tpu.analysis import semantics
        from accl_tpu.constants import DataType, TuningParams
        from accl_tpu.sequencer.plan import select_algorithm

        devs = jax.devices()
        if len(devs) < world:
            print(f"FAIL: degraded-mode leg needs {world} devices, have "
                  f"{len(devs)} (set XLA_FLAGS="
                  "--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
            sys.exit(1)
        accl = ACCL(Mesh(np.array(devs[:world]), ("ccl",)))
        n_deg = 4096
        deg_data = rng.integers(-32, 32,
                                size=(world, n_deg)).astype(np.float32)
        a = accl.create_buffer(n_deg, np.float32, deg_data)
        b = accl.create_buffer(n_deg, np.float32)
        accl.allreduce(a, b, n_deg, ReduceFunction.SUM,
                       mode="live_subset", live_ranks=survivors)
        deg_want = deg_data[list(survivors)].sum(0)
        degraded_ok = bool(np.array_equal(
            np.asarray(b.host), np.tile(deg_want, (world, 1))))
        deg_opts = CallOptions(
            scenario=Operation.allreduce, count=n_deg,
            function=int(ReduceFunction.SUM),
            data_type=DataType.float32, live_ranks=survivors)
        deg_plan = select_algorithm(
            Operation.allreduce, n_deg, 4, world,
            max_eager_size=1024, eager_rx_buf_size=1024,
            tuning=TuningParams.default(), live_ranks=survivors)
        deg_diags = semantics.certify_call(deg_opts, deg_plan, world)
        print(f"  degraded live_subset{tuple(survivors)}: bitwise "
              f"{'ok' if degraded_ok else 'WRONG'}, certifier "
              f"{'clean' if not deg_diags else [str(d) for d in deg_diags]}",
              file=sys.stderr)

        # -- leg 4: flat-vs-reconfigured crossover --------------------
        # staying on the dead world pays one derived deadline (plus the
        # guard's failure handling) per dispatch, forever; the measured
        # one-time reconfiguration cost amortizes after:
        reconfig_s = replan_s + comm_s
        per_dispatch_saving = deadline_s - t_rec_med
        crossover = (math.ceil(reconfig_s / per_dispatch_saving)
                     if per_dispatch_saving > 0 else None)
        print(f"  crossover: wedged {deadline_s * 1e3:.1f} ms vs "
              f"recovered {t_rec_med * 1e3:.2f} ms per dispatch; "
              f"reconfig {reconfig_s:.2f} s amortizes after "
              f"{crossover} dispatches", file=sys.stderr)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    print(json.dumps({
        "metric": "fault gate: mid-stream rank death detected by "
                  f"model-derived deadlines and recovered (w{world} "
                  "native TCP; certified replan + survivor "
                  "communicator + certified degraded mode)",
        "value": round(recovery_s, 3),
        "unit": "s to first post-recovery dispatch",
        "platform": "cpu-emulator",
        "deadline_ms": round(deadline_s * 1e3, 2),
        "predicted_ms": round(
            policy.predict_s("allreduce", count) * 1e3, 3),
        "residual_reference": round(ref, 4),
        "detect_attempts": attempts,
        "detect_s": round(detect_s, 3),
        "detect_budget_s": round(detect_budget, 3),
        "replan_s": round(replan_s, 3),
        "replan_source": rplan.source,
        "certificate": rplan.certificate,
        "survivors": list(survivors),
        "post_recovery_dispatch_ms": round(t_rec_med * 1e3, 3),
        "armed_overhead_pct": round(overhead * 100, 4),
        "armed_overhead_budget_pct": FAULT_OVERHEAD_BUDGET * 100,
        "armed_seam_ns_per_dispatch": round(seam_s * 1e9),
        "control_wall_delta_pct": round(wall_delta * 100, 2),
        "control_misses": len(mgr_probe.misses),
        "degraded_bitwise_ok": degraded_ok,
        "degraded_certifier_diags": len(deg_diags),
        "flat_vs_reconfigured_crossover_dispatches": crossover,
    }))
    if overhead >= FAULT_OVERHEAD_BUDGET:
        print(f"FAIL: the armed deadline seam costs "
              f"{overhead * 100:.2f}% of the per-dispatch median "
              f"(budget {FAULT_OVERHEAD_BUDGET * 100:.0f}%)",
              file=sys.stderr)
        sys.exit(1)
    if mgr_probe.misses:
        print(f"FAIL: {len(mgr_probe.misses)} false deadline misses on "
              "the no-fault control — a band that flags healthy "
              "dispatches would make every verdict untrustworthy",
              file=sys.stderr)
        sys.exit(1)
    if attempts != FAULT_RETRY_BUDGET + 1:
        print(f"FAIL: detection took {attempts} attempts, the budget "
              f"bounds it at {FAULT_RETRY_BUDGET + 1}", file=sys.stderr)
        sys.exit(1)
    if detect_s > detect_budget:
        print(f"FAIL: detection took {detect_s:.2f} s, over the "
              f"deadline-derived budget {detect_budget:.2f} s",
              file=sys.stderr)
        sys.exit(1)
    if flight.last_error_trace() is None:
        print("FAIL: no flight-recorder post-mortem was frozen for the "
              "deadline misses", file=sys.stderr)
        sys.exit(1)
    if not degraded_ok or deg_diags:
        print("FAIL: certified degraded mode wrong or uncertified "
              f"(bitwise={degraded_ok}, diags="
              f"{[str(d) for d in deg_diags]})", file=sys.stderr)
        sys.exit(1)
    if crossover is None:
        print("FAIL: a recovered dispatch does not beat the wedged "
              "deadline — reconfiguration would never amortize",
              file=sys.stderr)
        sys.exit(1)


# the chaos-gate contract (bench.py --chaos-gate): under a seeded
# loss/corrupt/dup/reorder mix the transport's reliability sublayer
# (CRC32C frames + selective retransmit, runtime.cpp) must absorb every
# transient wire fault BELOW the resilience layer — every collective
# answer bitwise, repair counters strictly positive, and ZERO false
# dead-rank escalations (any deadline miss must classify LOSSY ->
# IntegrityFault via the wire-health evidence, never reach the
# exclude->replan path) — while the no-fault CRC+ack bookkeeping stays
# under CHAOS_OVERHEAD_BUDGET of the per-dispatch median (the obs/fault
# gates' per-event-cost methodology; the rely-on vs rely-off A/B wall
# delta is reported unvarnished, not gated).  A genuinely dark wire
# (kill-rank) must still classify DARK, so the certified
# reconfiguration stays reachable for real deaths.
CHAOS_GATE_WORLD = 4
CHAOS_GATE_COUNT = 65536  # 256 KiB fp32: the fault gate's ms regime
CHAOS_LOSS_PCT = 1.0
CHAOS_CORRUPT_PCT = 0.5
CHAOS_DUP_PCT = 0.5
CHAOS_REORDER_PCT = 0.5
CHAOS_SEED = 1009
CHAOS_ROUNDS = 10
CHAOS_UDP_ROUNDS = 5  # the datagram-POE soak leg (same seam, same seed)
CHAOS_ITERS = 3  # dispatches per soak round (amortize thread spawn)
CHAOS_MISS_BUDGET = 6  # lossy-classified re-runs before giving up
CHAOS_CONTROL_ROUNDS = 10
CHAOS_OVERHEAD_BUDGET = 0.03


def _chaos_wire_totals(world_obj):
    """Sum every live rank's stats2 counter surface."""
    agg = {}
    for r in world_obj.ranks:
        if r is None:
            continue
        for k, v in r.wire_stats().items():
            agg[k] = agg.get(k, 0) + v
    return agg


def _chaos_gate_main():
    """bench.py --chaos-gate: the reliable-wire claims (CI, after
    --fault-gate):

      1. SEEDED CHAOS SOAK on the 4-rank native TCP world
         (ACCL_RT_FAULT_{LOSS,CORRUPT,DUP,REORDER}_PCT at 1/0.5/0.5/0.5
         + ACCL_RT_FAULT_SEED): lockstep allreduce rounds under armed
         model-derived deadlines. Every answer must be BITWISE vs the
         oracle; the repair counters (retransmits, CRC drops, dup
         drops) must be strictly positive (the faults provably fired
         AND were provably absorbed); and zero rounds may escalate to
         exclusion — a deadline miss under injected loss must classify
         LOSSY through the wire-health deltas (ResilienceManager
         .assess_miss -> IntegrityFault) and retry on the same
         membership, because a ~1 s certified reconfiguration is the
         wrong answer to a lost frame.

      2. NO-FAULT OVERHEAD: on a clean world the CRC+ack bookkeeping
         (the native rely_ns counter: CRC32C at both ends + health-tick
         work, summed across ranks) per lockstep dispatch must stay
         under 3% of the per-dispatch median. The rely-off A/B wall
         delta is reported unvarnished, not gated (host scheduler
         noise — the fault gate's posture).

      3. DARK-WIRE CONTROL: a killed rank's silence must classify DARK
         (no repair-activity delta on the survivors), so assess_miss
         falls through to the retry/exclude budget — the chaos policy
         cannot mask a real death.

    stdout: ONE JSON line {metric, value = soak dispatches, ...}."""
    from accl_tpu.constants import Operation
    from accl_tpu.descriptor import CallOptions
    from accl_tpu.device.emu_device import EmuWorld
    from accl_tpu.resilience import (
        DeadlineMissedError,
        DeadlinePolicy,
        NativeDeadlineGuard,
        ResilienceManager,
        RetryBudget,
    )
    from accl_tpu import ReduceFunction
    from accl_tpu.telemetry import calibrate_from_trace, wire_health_report
    from accl_tpu.telemetry import native as tnative
    from accl_tpu.telemetry.tracer import SCHEMA_VERSION

    world = CHAOS_GATE_WORLD
    count = CHAOS_GATE_COUNT
    rng = np.random.default_rng(29)
    xs = rng.integers(-32, 32, size=(world, count)).astype(np.float32)
    oracle = xs.sum(0)
    chaos_env = {
        "ACCL_RT_FAULT_LOSS_PCT": str(CHAOS_LOSS_PCT),
        "ACCL_RT_FAULT_CORRUPT_PCT": str(CHAOS_CORRUPT_PCT),
        "ACCL_RT_FAULT_DUP_PCT": str(CHAOS_DUP_PCT),
        "ACCL_RT_FAULT_REORDER_PCT": str(CHAOS_REORDER_PCT),
        "ACCL_RT_FAULT_SEED": str(CHAOS_SEED),
    }
    managed = ["ACCL_RT_TRACE", "ACCL_RT_RELY", "ACCL_RT_FAULT_KILL_RANK",
               "ACCL_RT_FAULT_KILL_AFTER", *chaos_env]
    saved = {k: os.environ.get(k) for k in managed}
    for k in managed:
        os.environ.pop(k, None)
    os.environ["ACCL_RT_TRACE"] = "1"
    wkw = dict(max_eager=tnative.DEFAULT_MAX_EAGER,
               rx_buf_bytes=tnative.DEFAULT_RX_BUF)
    try:
        # -- calibrate link + residual band on a clean world ----------
        wa = EmuWorld(world, transport="tcp", **wkw)
        try:
            _obs_sweep(wa, (count * 4,), 2)  # cold TCP sessions
            for r in wa.ranks:
                r.trace_read()
            _obs_sweep(wa, (count * 4,), 6)
            warm = _obs_drain_events(wa, link=None)
            link = calibrate_from_trace(
                {"schema": SCHEMA_VERSION, "spans": warm})
            _obs_sweep(wa, (count * 4,), 6)
            ref_events = _obs_drain_events(wa, link)
            residuals = [
                abs(ev["args"]["predicted_s"] - ev["args"]["measured_s"])
                / ev["args"]["measured_s"]
                for ev in ref_events
                if ev["args"].get("predicted_s")
                and ev["args"].get("measured_s", 0) > 0]
            policy = DeadlinePolicy(link, world=world,
                                    rx_buf_bytes=tnative.DEFAULT_RX_BUF,
                                    max_eager_size=tnative.DEFAULT_MAX_EAGER)
            ref = policy.arm_from_residuals("allreduce", residuals)
            deadline_s = policy.deadline_s("allreduce", count)
            print(f"  link: alpha {link.alpha * 1e6:.0f} us, beta "
                  f"{link.beta / 1e9:.2f} GB/s; residual ref {ref:.3f} "
                  f"-> deadline {deadline_s * 1e3:.1f} ms", file=sys.stderr)

            # -- leg 2a: no-fault control (rely ON, the default) ------
            t_ctrl = []
            s0 = _chaos_wire_totals(wa)
            for _ in range(CHAOS_CONTROL_ROUNDS):
                s, res = _fault_dispatch_round(wa, xs, count,
                                               iters=CHAOS_ITERS)
                t_ctrl.append(s)
                for out in res:
                    assert np.array_equal(out, oracle), \
                        "control (rely on) answer wrong"
            s1 = _chaos_wire_totals(wa)
            ctrl_dispatches = CHAOS_CONTROL_ROUNDS * CHAOS_ITERS
            # per-RANK bookkeeping per dispatch: rely_ns sums every
            # rank's CRC+ack work, but the ranks run concurrently — the
            # cost a lockstep dispatch's critical path pays is one
            # rank's share (the obs/fault gates' per-event-cost
            # methodology; the whole-world sum is reported too)
            rely_total_s = ((s1["rely_ns"] - s0["rely_ns"]) / 1e9
                            / ctrl_dispatches)
            rely_s_per_dispatch = rely_total_s / world
            per_dispatch = float(np.median(t_ctrl))
            overhead = rely_s_per_dispatch / max(per_dispatch, 1e-9)
            print(f"  no-fault CRC+ack bookkeeping "
                  f"{rely_s_per_dispatch * 1e6:.1f} us/rank/dispatch = "
                  f"{overhead * 100:.3f}% of the "
                  f"{per_dispatch * 1e3:.2f} ms/dispatch median "
                  f"(world total {rely_total_s * 1e6:.1f} us)",
                  file=sys.stderr)
        finally:
            wa.close()

        # -- leg 2b: rely-off A/B (reported, not gated) ---------------
        os.environ["ACCL_RT_RELY"] = "0"
        wb = EmuWorld(world, transport="tcp", **wkw)
        os.environ.pop("ACCL_RT_RELY", None)
        try:
            t_off = []
            for _ in range(CHAOS_CONTROL_ROUNDS):
                s, res = _fault_dispatch_round(wb, xs, count,
                                               iters=CHAOS_ITERS)
                t_off.append(s)
                for out in res:
                    assert np.array_equal(out, oracle), \
                        "control (rely off) answer wrong"
            wall_delta = per_dispatch / max(float(np.median(t_off)),
                                            1e-9) - 1.0
            print(f"  A/B wall delta rely-on vs rely-off "
                  f"{wall_delta * 100:+.2f}% (reported, not gated — "
                  "host noise)", file=sys.stderr)
        finally:
            wb.close()

        # -- leg 1: the seeded chaos soak, once per POE ---------------
        # the transports differ in everything below the seam (ordered
        # stream vs standalone datagrams, writev vs sendmmsg) but the
        # reliability sublayer above it is the same code — the soak must
        # hold bitwise with zero exclusions on BOTH engines
        def _soak_leg(transport_name, target_rounds):
            for k, v in chaos_env.items():
                os.environ[k] = v
            wx = EmuWorld(world, transport=transport_name, **wkw)
            for k in chaos_env:
                os.environ.pop(k, None)
            try:
                mgr = ResilienceManager(
                    world, policy=policy,
                    budget=RetryBudget(max_retries=1, backoff_base_s=0.02))
                guard = NativeDeadlineGuard(policy)
                for r in wx.ranks:
                    guard.arm(r, "allreduce", count)
                    mgr.observe_wire_health(r.rank, r.wire_stats())

                def soak_attempt(rank, i):
                    out = np.zeros(count, np.float32)
                    h = rank.start(CallOptions(
                        scenario=Operation.allreduce, count=count,
                        function=int(ReduceFunction.SUM), data_type=3),
                        op0=xs[i].copy(), res=out)
                    try:
                        guard.wait(rank, h, "allreduce", count)
                        return ("ok", out)
                    except DeadlineMissedError as e:
                        return ("miss", e.miss)

                soak_ok = 0
                lossy_misses = 0
                excludes = 0
                rounds_run = 0
                while soak_ok < target_rounds * CHAOS_ITERS:
                    rounds_run += 1
                    verdicts = wx.run(soak_attempt)
                    misses = [v[1] for v in verdicts if v[0] == "miss"]
                    if misses:
                        # the decision tree: wire-health deltas say LOSSY
                        # (repair activity climbing), so this is an
                        # IntegrityFault retry on the SAME membership —
                        # an exclusion here is a FALSE dead-rank verdict
                        deltas = [mgr.observe_wire_health(r.rank,
                                                          r.wire_stats())
                                  for r in wx.ranks]
                        action = mgr.assess_miss(
                            misses[0],
                            {k: sum(d.get(k, 0) for d in deltas)
                             for k in deltas[0]})
                        if action != "integrity":
                            excludes += 1
                            break
                        lossy_misses += 1
                        if lossy_misses > CHAOS_MISS_BUDGET:
                            break
                        continue
                    for out_pair in verdicts:
                        if not np.array_equal(out_pair[1], oracle):
                            print(f"FAIL: chaos soak ({transport_name}) "
                                  "answer not bitwise", file=sys.stderr)
                            sys.exit(1)
                    soak_ok += 1  # one lockstep dispatch per run()
                    # a completed round resets the lossy-credit streak
                    # and the retry budget — the note_recovery contract
                    mgr.note_recovery(None)
                totals = _chaos_wire_totals(wx)
                health = wire_health_report(
                    {r.rank: r.wire_stats() for r in wx.ranks})
                print(f"  soak [{transport_name}]: {rounds_run} rounds, "
                      f"{lossy_misses} lossy-classified misses, "
                      f"{excludes} exclusions; injected "
                      f"loss/corrupt/dup/reorder = {totals['inj_loss']}/"
                      f"{totals['inj_corrupt']}/{totals['inj_dup']}/"
                      f"{totals['inj_reorder']}; repaired: retx "
                      f"{totals['retx_sent']}, crc drops "
                      f"{totals['crc_drops']}, dup drops "
                      f"{totals['dup_drops']}, nack rtt "
                      f"{totals['nack_rx']}", file=sys.stderr)
                return {"ok": soak_ok, "lossy": lossy_misses,
                        "excludes": excludes, "rounds": rounds_run,
                        "totals": totals, "health": health,
                        "integrity_faults": len(mgr.integrity_faults)}
            finally:
                wx.close()

        tcp_soak = _soak_leg("tcp", CHAOS_ROUNDS)
        udp_soak = _soak_leg("udp", CHAOS_UDP_ROUNDS)
        soak_ok = tcp_soak["ok"]
        lossy_misses = tcp_soak["lossy"]
        excludes = tcp_soak["excludes"]
        totals = tcp_soak["totals"]
        health = tcp_soak["health"]

        # -- leg 3: dark-wire control (a real death stays a death) ----
        victim = world - 2
        os.environ["ACCL_RT_FAULT_KILL_RANK"] = str(victim)
        os.environ["ACCL_RT_FAULT_KILL_AFTER"] = "2"
        wd = EmuWorld(world, transport="tcp", **wkw)
        os.environ.pop("ACCL_RT_FAULT_KILL_RANK", None)
        os.environ.pop("ACCL_RT_FAULT_KILL_AFTER", None)
        try:
            mgr2 = ResilienceManager(world, policy=policy)
            guard2 = NativeDeadlineGuard(policy)
            for r in wd.ranks:
                guard2.arm(r, "allreduce", count)
            _s, res = _fault_dispatch_round(wd, xs, count, guard=guard2,
                                            iters=2)
            for out in res:
                assert np.array_equal(out, oracle), "pre-kill wrong"
            for r in wd.ranks:
                if r.rank != victim:
                    mgr2.observe_wire_health(r.rank, r.wire_stats())

            def dark_attempt(rank, i):
                if i == victim:
                    try:
                        out = np.zeros(count, np.float32)
                        rank.allreduce(xs[i].copy(), out, count,
                                       ReduceFunction.SUM)
                    except Exception:
                        pass
                    return None
                out = np.zeros(count, np.float32)
                h = rank.start(CallOptions(
                    scenario=Operation.allreduce, count=count,
                    function=int(ReduceFunction.SUM), data_type=3),
                    op0=xs[i].copy(), res=out)
                try:
                    guard2.wait(rank, h, "allreduce", count)
                    return ("ok", out)
                except DeadlineMissedError as e:
                    return ("miss", e.miss)

            verdicts = wd.run(dark_attempt)
            dark_misses = [v[1] for v in verdicts
                           if v is not None and v[0] == "miss"]
            deltas = [mgr2.observe_wire_health(r.rank, r.wire_stats())
                      for r in wd.ranks if r.rank != victim]
            dark_delta = {k: sum(d.get(k, 0) for d in deltas)
                          for k in deltas[0]}
            dark_class = ResilienceManager.classify_wire_delta(dark_delta)
            # gate the bounded escalation OUTCOME, not one window's
            # bit-exact classification: a scheduler stall among healthy
            # survivors can leak a spurious retransmit/dup into the
            # kill window (a lossy-looking delta), but the integrity
            # budget must bound that credit — within budget+1
            # assessments the action walks the retry/exclude path,
            # because re-observing a dead wire yields a frozen,
            # repair-free delta
            dark_action = "none"
            dark_assessments = 0
            if dark_misses:
                for _ in range(mgr2.integrity_budget + 1):
                    dark_assessments += 1
                    dark_action = mgr2.assess_miss(dark_misses[0],
                                                   dark_delta)
                    if dark_action != "integrity":
                        break
                    deltas = [mgr2.observe_wire_health(r.rank,
                                                       r.wire_stats())
                              for r in wd.ranks if r.rank != victim]
                    dark_delta = {k: sum(d.get(k, 0) for d in deltas)
                                  for k in deltas[0]}
            print(f"  dark-wire control: {len(dark_misses)} survivor "
                  f"misses, first window classified {dark_class!r}, "
                  f"assess -> {dark_action!r} after {dark_assessments} "
                  "assessment(s) (the retry/exclude budget, not "
                  "unbounded IntegrityFault)", file=sys.stderr)
        finally:
            wd.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    print(json.dumps({
        "metric": "chaos gate: seeded loss/corrupt/dup/reorder absorbed "
                  f"at the transport (w{world} native TCP + UDP POEs; "
                  "bitwise answers, zero dead-rank escalations, CRC+ack "
                  "overhead gated)",
        "value": soak_ok + udp_soak["ok"],
        "unit": "bitwise lockstep dispatches under chaos",
        "platform": "cpu-emulator",
        "fault_mix_pct": {"loss": CHAOS_LOSS_PCT,
                          "corrupt": CHAOS_CORRUPT_PCT,
                          "dup": CHAOS_DUP_PCT,
                          "reorder": CHAOS_REORDER_PCT,
                          "seed": CHAOS_SEED},
        "injected": {k: totals[k] for k in
                     ("inj_loss", "inj_corrupt", "inj_dup",
                      "inj_reorder")},
        "repaired": {k: totals[k] for k in
                     ("retx_sent", "retx_miss", "crc_drops",
                      "dup_drops", "nack_sent", "nack_rx")},
        "wire_health_totals": health["totals"],
        "lossy_classified_misses": lossy_misses,
        "integrity_faults": tcp_soak["integrity_faults"],
        "false_dead_rank_escalations": excludes,
        "udp_soak": {
            "bitwise_dispatches": udp_soak["ok"],
            "lossy_classified_misses": udp_soak["lossy"],
            "false_dead_rank_escalations": udp_soak["excludes"],
            "injected": {k: udp_soak["totals"][k] for k in
                         ("inj_loss", "inj_corrupt", "inj_dup",
                          "inj_reorder")},
            "repaired": {k: udp_soak["totals"][k] for k in
                         ("retx_sent", "crc_drops", "dup_drops")}},
        "rely_us_per_rank_dispatch": round(rely_s_per_dispatch * 1e6, 2),
        "rely_us_world_total_dispatch": round(rely_total_s * 1e6, 2),
        "rely_overhead_pct": round(overhead * 100, 4),
        "rely_overhead_budget_pct": CHAOS_OVERHEAD_BUDGET * 100,
        "rely_off_wall_delta_pct": round(wall_delta * 100, 2),
        "deadline_ms": round(deadline_s * 1e3, 2),
        "dark_wire_first_window_class": dark_class,
        "dark_wire_action": dark_action,
        "dark_wire_assessments": dark_assessments,
        "dark_survivor_misses": len(dark_misses),
    }))
    fails = []
    if soak_ok < CHAOS_ROUNDS * CHAOS_ITERS:
        fails.append(f"soak completed only {soak_ok} bitwise dispatches "
                     f"(wanted {CHAOS_ROUNDS * CHAOS_ITERS}; "
                     f"{lossy_misses} lossy misses, {excludes} "
                     "exclusions)")
    if excludes:
        fails.append(f"{excludes} FALSE dead-rank escalations under "
                     "injected loss below the threshold — a lost frame "
                     "must never cost a certified reconfiguration")
    if not (totals["inj_loss"] > 0 and totals["inj_corrupt"] > 0
            and totals["inj_dup"] > 0):
        fails.append(f"fault model did not fire across the soak "
                     f"(loss/corrupt/dup = {totals['inj_loss']}/"
                     f"{totals['inj_corrupt']}/{totals['inj_dup']})")
    if not (totals["retx_sent"] > 0 and totals["crc_drops"] > 0
            and totals["dup_drops"] > 0):
        fails.append("repair counters not strictly positive (retx "
                     f"{totals['retx_sent']}, crc {totals['crc_drops']}, "
                     f"dup {totals['dup_drops']})")
    if udp_soak["ok"] < CHAOS_UDP_ROUNDS * CHAOS_ITERS:
        fails.append(f"UDP soak completed only {udp_soak['ok']} bitwise "
                     f"dispatches (wanted {CHAOS_UDP_ROUNDS * CHAOS_ITERS}; "
                     f"{udp_soak['lossy']} lossy misses, "
                     f"{udp_soak['excludes']} exclusions)")
    if udp_soak["excludes"]:
        fails.append(f"{udp_soak['excludes']} FALSE dead-rank "
                     "escalations on the UDP POE — the datagram engine "
                     "must absorb chaos below the resilience layer too")
    if not (udp_soak["totals"]["inj_loss"] > 0
            and udp_soak["totals"]["retx_sent"] > 0):
        fails.append("UDP soak faults did not provably fire+repair "
                     f"(inj_loss {udp_soak['totals']['inj_loss']}, retx "
                     f"{udp_soak['totals']['retx_sent']})")
    if overhead >= CHAOS_OVERHEAD_BUDGET:
        fails.append(f"no-fault CRC+ack bookkeeping costs "
                     f"{overhead * 100:.2f}% of the per-dispatch median "
                     f"(budget {CHAOS_OVERHEAD_BUDGET * 100:.0f}%)")
    if not dark_misses:
        fails.append("dark-wire control produced no survivor deadline "
                     "misses — the kill lever did not bite")
    if dark_action not in ("retry", "exclude"):
        fails.append(f"a killed rank never reached the retry/exclude "
                     f"budget (action {dark_action!r} after "
                     f"{dark_assessments} assessments) — the chaos "
                     "policy must never mask a real death")
    if fails:
        for f in fails:
            print(f"FAIL: {f}", file=sys.stderr)
        sys.exit(1)


# the wire-gate contract (bench.py --wire-gate): the vectored wire
# (scatter-gather writev transmit, multi-frame batching, zero payload
# coalescing copies — transport.cpp behind the POE seam) must BEAT the
# legacy per-frame cost model (ACCL_RT_WIRE_LEGACY=1: one header send +
# one payload send per frame, payload coalesced through a staging copy)
# on the same 4-rank native TCP world, interleaved world creations and
# medians so host drift cannot fake the win. Both legs run rely-off:
# this is a pure transport A/B, no CRC/ack confound. Gated: >= 2x jumbo
# (16 MiB) p2p throughput AND a measured small-message (4 KiB) RTT cut;
# 1 MiB throughput is reported ungated. The stats2 counters must agree
# with the story (vectored leg batched frames, legacy leg copied
# payload bytes) so the gate cannot pass by measuring the wrong path.
WIRE_GATE_WORLD = 4
WIRE_GATE_TRIALS = 5
WIRE_GATE_JUMBO_BYTES = 16 << 20
WIRE_GATE_MID_BYTES = 1 << 20
WIRE_GATE_SMALL_BYTES = 4096
WIRE_GATE_JUMBO_REPS = 3
WIRE_GATE_MID_REPS = 8
WIRE_GATE_RTT_REPS = 200
WIRE_GATE_JUMBO_SPEEDUP = 2.0  # ISSUE 16 acceptance: >= 2x at 16 MiB
WIRE_GATE_RTT_FACTOR = 0.97    # vectored RTT must cut >= 3% off legacy
# mixed-traffic leg: the 4 KiB ping-pong measured while a bulk stream
# to the SAME peer occupies the wire head — the HOL-blocking relief the
# per-peer lane model (ACCL_RT_LANES=2, docs/architecture.md) claims.
# Reported row, not gated: loopback TCP's tiny transit makes the relief
# magnitude platform-noisy even though its sign is structural.
WIRE_GATE_MIXED_BULK_BYTES = 256 << 10
WIRE_GATE_MIXED_REPS = 64


def _wire_gate_trial(transport, legacy, check_payload=False, lanes=None,
                     mixed_only=False):
    """One world's worth of p2p measurements: 16 MiB + 1 MiB one-way
    throughput (rank 0 -> 1, closed by a tiny ack so the sender's clock
    spans the full drain), the 4 KiB ping-pong RTT, and the mixed-traffic
    RTT (the same ping-pong with a 256 KiB bulk send to the same peer
    immediately ahead of each ping — the bulk rides the lane-1 bulk
    stream when `lanes=2`, so the ping is not serialized behind it).
    Returns a dict of medians-ready numbers plus the sender's
    transmit-shape counters; `mixed_only` skips the throughput/RTT legs
    for the lanes-A/B world."""
    from accl_tpu.device.emu_device import EmuWorld

    managed = {"ACCL_RT_RELY": "0"}
    if legacy:
        managed["ACCL_RT_WIRE_LEGACY"] = "1"
    if lanes is not None:
        managed["ACCL_RT_LANES"] = str(lanes)
    saved = {k: os.environ.get(k) for k in managed}
    for k, v in managed.items():
        os.environ[k] = v
    try:
        w = EmuWorld(WIRE_GATE_WORLD, transport=transport,
                     max_eager=32 << 20, max_rndzv=64 << 20)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        out = {}

        n_small = WIRE_GATE_SMALL_BYTES // 4
        small = np.arange(n_small, dtype=np.int32)

        def thru_body(nbytes, reps, tag):
            n = nbytes // 4
            data = (np.arange(n, dtype=np.int64) * 2654435761
                    % 2147483629).astype(np.int32)
            ack = np.zeros(1, np.int32)

            def body(rank, i):
                if i == 0:
                    rank.send(data, n, 1, tag=tag)  # warm the lane
                    rank.recv(ack, 1, 1, tag=tag + 1)
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        rank.send(data, n, 1, tag=tag)
                    rank.recv(ack, 1, 1, tag=tag + 1)
                    return nbytes * reps / (time.perf_counter() - t0)
                if i == 1:
                    buf = np.zeros(n, np.int32)
                    rank.recv(buf, n, 0, tag=tag)
                    rank.send(ack, 1, 0, tag=tag + 1)
                    for _ in range(reps):
                        rank.recv(buf, n, 0, tag=tag)
                    rank.send(ack, 1, 0, tag=tag + 1)
                    if check_payload:
                        assert np.array_equal(buf, data), \
                            "wire-gate payload not bitwise"
                return None

            return w.run(body)[0]

        if not mixed_only:
            out["jumbo_gbps"] = thru_body(WIRE_GATE_JUMBO_BYTES,
                                          WIRE_GATE_JUMBO_REPS, 21) / 1e9
            out["mid_gbps"] = thru_body(WIRE_GATE_MID_BYTES,
                                        WIRE_GATE_MID_REPS, 31) / 1e9

        def rtt_body(rank, i):
            buf = np.zeros(n_small, np.int32)
            if i == 0:
                rank.send(small, n_small, 1, tag=41)  # warm
                rank.recv(buf, n_small, 1, tag=42)
                t0 = time.perf_counter()
                for _ in range(WIRE_GATE_RTT_REPS):
                    rank.send(small, n_small, 1, tag=41)
                    rank.recv(buf, n_small, 1, tag=42)
                return (time.perf_counter() - t0) / WIRE_GATE_RTT_REPS
            if i == 1:
                for _ in range(WIRE_GATE_RTT_REPS + 1):
                    rank.recv(buf, n_small, 0, tag=41)
                    rank.send(buf, n_small, 0, tag=42)
            return None

        if not mixed_only:
            out["rtt_s"] = w.run(rtt_body)[0]

        nb = WIRE_GATE_MIXED_BULK_BYTES // 4
        bulk = np.zeros(nb, np.int32)
        # the bulk message rides the lane-1 bulk stream only when two
        # lanes are up (>= ACCL_RT_LANE_BULK_BYTES); on one lane the
        # stream completes in wire order ONLY, so the receiver must
        # drain the bulk before the ping can match — that forced drain
        # IS the HOL cost the lanes remove, and the receiver's drain
        # order below is each config's fastest legal one
        two_lanes = lanes is not None and int(lanes) >= 2

        def mixed_body(rank, i):
            buf = np.zeros(n_small, np.int32)
            bulkbuf = np.zeros(nb, np.int32)
            reps = WIRE_GATE_MIXED_REPS
            if i == 0:
                rank.send(bulk, nb, 1, tag=51)  # warm
                rank.send(small, n_small, 1, tag=61)
                rank.recv(buf, n_small, 1, tag=62)
                total = 0.0
                for _ in range(reps):
                    rank.send(bulk, nb, 1, tag=51)
                    t0 = time.perf_counter()
                    rank.send(small, n_small, 1, tag=61)
                    rank.recv(buf, n_small, 1, tag=62)
                    total += time.perf_counter() - t0
                return total / reps
            if i == 1:
                for _ in range(reps + 1):
                    if two_lanes:
                        # answer the ping ahead of the unconsumed bulk
                        rank.recv(buf, n_small, 0, tag=61)
                        rank.send(buf, n_small, 0, tag=62)
                        rank.recv(bulkbuf, nb, 0, tag=51)
                    else:
                        rank.recv(bulkbuf, nb, 0, tag=51)
                        rank.recv(buf, n_small, 0, tag=61)
                        rank.send(buf, n_small, 0, tag=62)
            return None

        out["mixed_rtt_s"] = w.run(mixed_body)[0]
        s = w.ranks[0].wire_stats()
        out["tx_syscalls"] = s["tx_syscalls"]
        out["tx_batched"] = s["tx_batched"]
        out["tx_frames"] = s["tx_frames"]
        return out
    finally:
        w.close()


def _wire_gate_main():
    """bench.py --wire-gate: the zero-copy vectored wire's measured
    claims (ISSUE 16 acceptance), CI-gated. Interleaved legacy/vectored
    world creations, medians over WIRE_GATE_TRIALS trials each:

      1. JUMBO THROUGHPUT: 16 MiB eager p2p on the 4-rank native TCP
         world must run >= 2x the legacy wire (per-frame syscalls +
         coalescing copies vs one writev per ~hundreds of frames with
         borrowed payload pointers).

      2. LATENCY FLOOR: the 4 KiB ping-pong RTT median must come in
         measurably under legacy (one vectored syscall per frame vs
         legacy's header+payload send pair) — the cut is gated, the
         magnitude reported.

      3. SHAPE EVIDENCE: the vectored leg's stats2 counters must show
         multi-frame batching (tx_batched > 0, tx_syscalls well under
         tx_frames) and the legacy leg must show none — the gate fails
         if either leg measured the wrong code path.

    1 MiB throughput is reported unvarnished (mid-size frames amortize
    the syscall tax less; the number tracks the trend, not a gate).
    The mixed-traffic RTT row (4 KiB ping behind a 256 KiB bulk send to
    the same peer, vectored wire with 1 vs 2 lanes) is reported, not
    gated: it is the HOL-blocking claim of the per-peer lane model
    under load, but loopback transit makes the magnitude noisy.
    stdout: ONE JSON line {metric, value = jumbo speedup, ...}."""
    legs = {"legacy": [], "vectored": [], "lanes2": []}
    for trial in range(WIRE_GATE_TRIALS):
        for name in ("legacy", "vectored", "lanes2"):  # interleaved:
            # drift-proof — every config samples every host-load epoch
            r = _wire_gate_trial("tcp", legacy=(name == "legacy"),
                                 check_payload=(trial == 0
                                                and name != "lanes2"),
                                 lanes=2 if name == "lanes2" else None,
                                 mixed_only=(name == "lanes2"))
            legs[name].append(r)
            if name == "lanes2":
                print(f"  trial {trial} {name}: mixed rtt "
                      f"{r['mixed_rtt_s'] * 1e6:.1f} us",
                      file=sys.stderr)
                continue
            print(f"  trial {trial} {name}: jumbo "
                  f"{r['jumbo_gbps']:.2f} GB/s, 1MiB "
                  f"{r['mid_gbps']:.2f} GB/s, rtt "
                  f"{r['rtt_s'] * 1e6:.1f} us, mixed rtt "
                  f"{r['mixed_rtt_s'] * 1e6:.1f} us  "
                  f"(tx syscalls/frames "
                  f"{r['tx_syscalls']}/{r['tx_frames']}, batched "
                  f"{r['tx_batched']})", file=sys.stderr)

    med = {name: {k: float(np.median([t[k] for t in ts]))
                  for k in ts[0] if k.endswith(("_gbps", "_s"))}
           for name, ts in legs.items()}
    speedup16 = med["vectored"]["jumbo_gbps"] / med["legacy"]["jumbo_gbps"]
    speedup1 = med["vectored"]["mid_gbps"] / med["legacy"]["mid_gbps"]
    rtt_ratio = med["vectored"]["rtt_s"] / med["legacy"]["rtt_s"]
    mixed_relief = (1 - med["lanes2"]["mixed_rtt_s"]
                    / med["vectored"]["mixed_rtt_s"]) * 100
    vec_last = legs["vectored"][-1]
    leg_last = legs["legacy"][-1]
    print(f"  medians: jumbo {med['legacy']['jumbo_gbps']:.2f} -> "
          f"{med['vectored']['jumbo_gbps']:.2f} GB/s ({speedup16:.2f}x), "
          f"1MiB {med['legacy']['mid_gbps']:.2f} -> "
          f"{med['vectored']['mid_gbps']:.2f} GB/s ({speedup1:.2f}x), "
          f"rtt {med['legacy']['rtt_s'] * 1e6:.1f} -> "
          f"{med['vectored']['rtt_s'] * 1e6:.1f} us "
          f"({(1 - rtt_ratio) * 100:+.1f}% cut), mixed rtt "
          f"{med['vectored']['mixed_rtt_s'] * 1e6:.1f} -> "
          f"{med['lanes2']['mixed_rtt_s'] * 1e6:.1f} us 1->2 lanes "
          f"({mixed_relief:+.1f}% relief)", file=sys.stderr)

    print(json.dumps({
        "metric": "wire gate: zero-copy vectored transmit vs legacy "
                  f"per-frame wire (w{WIRE_GATE_WORLD} native TCP p2p, "
                  "interleaved medians; jumbo throughput + RTT floor "
                  "gated, transmit shape cross-checked)",
        "value": round(speedup16, 2),
        "unit": "x jumbo (16 MiB) throughput vs legacy wire",
        "platform": "cpu-emulator",
        "trials": WIRE_GATE_TRIALS,
        "jumbo_gbps": {k: round(m["jumbo_gbps"], 3)
                       for k, m in med.items() if "jumbo_gbps" in m},
        "mid_gbps": {k: round(m["mid_gbps"], 3)
                     for k, m in med.items() if "mid_gbps" in m},
        "rtt_us": {k: round(m["rtt_s"] * 1e6, 1)
                   for k, m in med.items() if "rtt_s" in m},
        "mixed_rtt_us": {
            "one_lane": round(med["vectored"]["mixed_rtt_s"] * 1e6, 1),
            "two_lanes": round(med["lanes2"]["mixed_rtt_s"] * 1e6, 1)},
        "mixed_rtt_relief_pct": round(mixed_relief, 2),
        "mixed_bulk_bytes": WIRE_GATE_MIXED_BULK_BYTES,
        "jumbo_speedup": round(speedup16, 2),
        "mid_speedup": round(speedup1, 2),
        "rtt_cut_pct": round((1 - rtt_ratio) * 100, 2),
        "jumbo_speedup_floor": WIRE_GATE_JUMBO_SPEEDUP,
        "rtt_factor_ceiling": WIRE_GATE_RTT_FACTOR,
        "tx_shape": {
            "vectored": {k: vec_last[k] for k in
                         ("tx_syscalls", "tx_batched", "tx_frames")},
            "legacy": {k: leg_last[k] for k in
                       ("tx_syscalls", "tx_batched", "tx_frames")}},
    }))
    fails = []
    if speedup16 < WIRE_GATE_JUMBO_SPEEDUP:
        fails.append(f"jumbo (16 MiB) speedup {speedup16:.2f}x under the "
                     f"{WIRE_GATE_JUMBO_SPEEDUP}x floor "
                     f"({med['legacy']['jumbo_gbps']:.2f} -> "
                     f"{med['vectored']['jumbo_gbps']:.2f} GB/s)")
    if rtt_ratio > WIRE_GATE_RTT_FACTOR:
        fails.append(f"small-message RTT not cut: vectored/legacy = "
                     f"{rtt_ratio:.3f} (ceiling {WIRE_GATE_RTT_FACTOR}; "
                     f"{med['legacy']['rtt_s'] * 1e6:.1f} -> "
                     f"{med['vectored']['rtt_s'] * 1e6:.1f} us)")
    if not (vec_last["tx_batched"] > 0
            and vec_last["tx_syscalls"] < vec_last["tx_frames"]):
        fails.append("vectored leg shows no multi-frame batching "
                     f"(syscalls {vec_last['tx_syscalls']}, frames "
                     f"{vec_last['tx_frames']}, batched "
                     f"{vec_last['tx_batched']}) — wrong code path?")
    if leg_last["tx_batched"] != 0:
        fails.append(f"legacy leg batched {leg_last['tx_batched']} "
                     "frames — ACCL_RT_WIRE_LEGACY did not pin the "
                     "baseline cost model")
    if fails:
        for f in fails:
            print(f"FAIL: {f}", file=sys.stderr)
        sys.exit(1)


# --serve-gate: the latency-floor decode path at production request
# rates (ISSUE 18 acceptance). Two worlds, four measured claims:
#   mesh leg (virtual 8-dev XLA mesh, memcpy wire): batched continuous-
#   batching decode is BITWISE-equal to sequential per-request decode
#   and to the dispatch-per-layer eager twin; the fused one-dispatch
#   step beats the eager form at equal plans (interleaved medians);
#   tokens/s + step-latency tail (p50/p99/p99.9 through the telemetry
#   histograms) reported; a committed latency-grid library entry is
#   SELECTED by the calibrated SYNTH_LATENCY_MAX_COUNT window and wins
#   its 1-64 KiB cell by predicted time (gated) — its measured time on
#   this memcpy-wire mesh is reported unvarnished, not gated (the
#   alpha the lat schedules cut is not this mesh's cost structure).
#   WAN leg (shaped 4-rank native TCP world): the decode step's
#   collective fingerprint (2 allreduces/layer at B*d_model fp32)
#   soaked back to back — the alpha-dominated regime the latency work
#   targets — gating the p99 step tail under an absolute ceiling.
SERVE_GATE_BATCH = 4
SERVE_GATE_MAX_LEN = 24
SERVE_GATE_STEPS = 32          # interleaved fused/eager timing steps
SERVE_GATE_FUSED_SPEEDUP = 1.05
SERVE_GATE_TOKENS_S_FLOOR = 1.0
SERVE_GATE_LAT_BYTES = 8192    # decode-sized allreduce cell (1-64 KiB)
SERVE_GATE_LAT_ROUNDS = 24
SERVE_GATE_WAN_STEPS = 48
SERVE_GATE_WAN_P99_CEILING_S = 1.0

# -- the multi-tenant gate (bench.py --tenant-gate) -------------------
#   8 interactive tenants (priority 0, 8 KiB fp32 allreduces in paced
#   waves over per-tenant arenas) share the scheduler with one bulk
#   tenant (priority 1) pushing >= 1 GiB of ring-wire traffic — the
#   footprint summaries carry no byte counts, so wire bytes are the
#   ring identity 2*(world-1)*payload per allreduce chunk. Gated:
#   the WORST small-tenant p99 stays inside the committed band (solo
#   p99 x TENANT_GATE_P99_BAND plus TENANT_GATE_HOL_CHUNKS bulk chunks
#   of head-of-line allowance — tpu_device holds the launch mutex for
#   a WHOLE XLA step, so a small dispatch admitted behind an in-flight
#   chunk waits it out; that is the device's cost structure, and the
#   chunk size bounds it); zero uncertified concurrent dispatches with
#   at least one certified overlap (every interleaving under a
#   certificate id); the bulk tenant moved its full wire budget; a
#   deterministic WFQ prefix check holds the 4:1 share inside
#   tolerance; saturation stays a typed error. The band/weights config
#   is committed in BASELINE_BENCH.json's "tenant" block — bench
#   --check fails on drift, so a retune is a reviewed diff.
TENANT_GATE_SMALL_TENANTS = 8
TENANT_GATE_SMALL_COUNT = 2048        # 8 KiB fp32 per small dispatch
TENANT_GATE_WAVES = 12
TENANT_GATE_WAVE_GAP_S = 2.0
TENANT_GATE_BULK_WIRE_BYTES = 1 << 30
TENANT_GATE_BULK_CHUNK_ELEMS = 128 * 1024   # 512 KiB fp32 payload
TENANT_GATE_WORKERS = 2
TENANT_GATE_P99_BAND = 3.0            # x the solo small-tenant p99
TENANT_GATE_HOL_CHUNKS = 2.0          # + bulk chunks of HOL allowance
TENANT_GATE_FAIR_SHARE_TOL = 0.05
TENANT_GATE_SOAK_TIMEOUT_S = 480.0


def _serve_gate_cfg(trf):
    """The serve-gate model: small enough for CI wall clock, shaped so
    TP is real on the full 8-dev mesh (GQA 2:1, world | heads/kv/ff)."""
    return trf.TransformerConfig(vocab=256, d_model=64, n_heads=16,
                                 n_kv_heads=8, n_layers=4, d_ff=256,
                                 dtype="float32")


def _serve_gate_main():
    """bench.py --serve-gate: see the constants block above for the
    claims. stdout: ONE JSON line {metric, value = fused-vs-eager
    speedup, parity verdicts, tokens/s, latency tails, lat-cell
    selection + predicted/measured times}."""
    import jax
    from jax.sharding import Mesh

    from accl_tpu import ReduceFunction
    from accl_tpu.accl import ACCL
    from accl_tpu.constants import (
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        DataType,
        Operation,
        TuningParams,
    )
    from accl_tpu.descriptor import CallOptions
    from accl_tpu.device.emu_device import EmuWorld
    from accl_tpu.models import serve
    from accl_tpu.models import transformer as trf
    from accl_tpu.sequencer import synthesis as synth
    from accl_tpu.sequencer.lowering import ScheduleCompiler
    from accl_tpu.sequencer.plan import Algorithm, select_algorithm
    from accl_tpu.sequencer.timing import tuning_crossovers
    from accl_tpu.telemetry import native as tnative
    from accl_tpu.telemetry.metrics import MetricsRegistry, quantile_key

    fails = []
    world = min(len(jax.devices()), 8)
    mesh = Mesh(np.array(jax.devices()[:world]), axis_names=("ccl",))
    cfg = _serve_gate_cfg(trf)
    params = jax.tree.map(np.asarray,
                          trf.init_params(cfg, jax.random.key(0)))
    rng = np.random.default_rng(2718)
    prompts = [list(map(int, rng.integers(1, cfg.vocab,
                                          int(rng.integers(1, 6)))))
               for _ in range(8)]
    max_new = 6

    # 1. PARITY (gated, bitwise): batched continuous batching ==
    # sequential per-request decode == the eager dispatch-per-layer twin
    def run_tokens(mode, sequential):
        srv = serve.DecodeServer(ACCL(mesh), cfg, params,
                                 batch=SERVE_GATE_BATCH,
                                 max_len=SERVE_GATE_MAX_LEN, mode=mode,
                                 registry=MetricsRegistry())
        if sequential:
            outs = []
            for p in prompts:
                outs.extend(serve.generate(srv, [p], max_new))
            return outs
        return serve.generate(srv, prompts, max_new)

    batched = run_tokens("fused", sequential=False)
    sequential = run_tokens("fused", sequential=True)
    eager = run_tokens("eager", sequential=False)
    parity_seq = batched == sequential
    parity_eager = batched == eager
    if not parity_seq:
        fails.append("batched decode != sequential decode (ragged "
                     "join/leave changed tokens)")
    if not parity_eager:
        fails.append("fused decode != eager layer-by-layer decode")
    print(f"  parity: batched==sequential {parity_seq}, fused==eager "
          f"{parity_eager} ({len(prompts)} ragged requests over "
          f"{SERVE_GATE_BATCH} slots)", file=sys.stderr)

    # 2. FUSED vs EAGER at sustained occupancy (gated, interleaved
    # medians) + tokens/s + the step-latency tail through the
    # telemetry histograms (p99.9 is the new nearest-rank tail row)
    load = [list(map(int, rng.integers(1, cfg.vocab, 2)))
            for _ in range(12)]

    def mk(mode):
        reg = MetricsRegistry()
        srv = serve.DecodeServer(ACCL(mesh), cfg, params,
                                 batch=SERVE_GATE_BATCH,
                                 max_len=SERVE_GATE_MAX_LEN, mode=mode,
                                 registry=reg)
        for p in load:
            srv.submit(p, 10)
        return srv, reg

    srv_f, reg_f = mk("fused")
    srv_e, _reg_e = mk("eager")
    srv_f.step()  # first dispatch pays compile/registration: warm both
    srv_e.step()
    dt_f, dt_e, gen_f = [], [], 0
    for _ in range(SERVE_GATE_STEPS):
        t0 = time.perf_counter()
        gen_f += srv_f.step()
        dt_f.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        srv_e.step()
        dt_e.append(time.perf_counter() - t0)
    med_f = float(np.median(dt_f))
    med_e = float(np.median(dt_e))
    speedup = med_e / med_f
    tokens_s = gen_f / sum(dt_f)
    # tail through the telemetry histogram path (p99.9 is the new
    # nearest-rank row) over the steady-state steps only — the
    # compile-paying warm step is not a serving latency
    treg = MetricsRegistry()
    th = treg.histogram("accl_serve_step_seconds", mode="fused",
                        batch=SERVE_GATE_BATCH)
    for t in dt_f:
        th.observe(t)
    hrow = treg.snapshot()["histograms"]["accl_serve_step_seconds"][0]
    tail = {quantile_key(q): hrow.get(quantile_key(q))
            for q in (0.5, 0.99, 0.999)}
    assert reg_f.snapshot()["histograms"]["accl_serve_step_seconds"], \
        "DecodeServer stopped reporting step latency to its registry"
    if speedup < SERVE_GATE_FUSED_SPEEDUP:
        fails.append(f"fused step speedup {speedup:.2f}x under the "
                     f"{SERVE_GATE_FUSED_SPEEDUP}x floor (eager "
                     f"{med_e * 1e3:.2f} -> fused {med_f * 1e3:.2f} "
                     "ms/step)")
    if tokens_s < SERVE_GATE_TOKENS_S_FLOOR:
        fails.append(f"decode throughput {tokens_s:.2f} tok/s under "
                     f"the {SERVE_GATE_TOKENS_S_FLOOR} floor")
    print(f"  fused {med_f * 1e3:.2f} ms/step vs eager "
          f"{med_e * 1e3:.2f} ms/step ({speedup:.2f}x), "
          f"{tokens_s:.1f} tok/s at {SERVE_GATE_BATCH} slots; step "
          f"p50 {hrow.get('p50', 0) * 1e3:.2f} p99 "
          f"{hrow.get('p99', 0) * 1e3:.2f} p99.9 "
          f"{hrow.get('p99_9', 0) * 1e3:.2f} ms", file=sys.stderr)

    # 3. the LATENCY-GRID cell (selection + predicted win gated;
    # measured reported unvarnished): the calibrated window must admit
    # a committed lat entry at a decode-sized payload and predict it
    # beats both the hand-written best and any std-grid entry there
    link = _shipped_link()
    tuning_lat = TuningParams.from_crossovers(
        tuning_crossovers(link, world=world))
    window = int(tuning_lat.synth_latency_max_count)
    nbytes = min(SERVE_GATE_LAT_BYTES, window)
    count = max(nbytes // 4, 1)
    kw = dict(max_eager_size=DEFAULT_MAX_EAGER_SIZE,
              eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE)
    lat_cell = {"window_bytes": window, "nbytes": nbytes}
    if window <= 0:
        fails.append("SYNTH_LATENCY_MAX_COUNT register is closed under "
                     "the shipped link — no latency window to serve "
                     "decode traffic from")
    else:
        plan_lat = select_algorithm(Operation.allreduce, count, 4,
                                    world, tuning=tuning_lat, **kw)
        key = plan_lat.synth_key \
            if plan_lat.algorithm == Algorithm.SYNTHESIZED else None
        spec = synth.entry_for_key(key).spec if key else None
        if spec is None or spec.grid != "lat":
            fails.append(
                f"lat cell ({nbytes} B, w{world}): selection inside "
                f"the calibrated window picked "
                f"{key or plan_lat.algorithm.name}, not a latency-grid "
                "entry")
        else:
            t_lat = synth.predict_spec(link, spec, count, 4)
            t_hand = synth.hand_written_best(link, Operation.allreduce,
                                             count, 4, world)
            std_key = synth.select_entry(Operation.allreduce, world,
                                         nbytes)
            t_std = (synth.predict_spec(
                link, synth.entry_for_key(std_key).spec, count, 4)
                if std_key else float("inf"))
            lat_cell.update(
                key=key, predicted_lat_us=round(t_lat * 1e6, 1),
                predicted_hand_us=round(t_hand * 1e6, 1),
                predicted_std_us=(round(t_std * 1e6, 1)
                                  if std_key else None))
            # the win that matters: beat the hand-written best the
            # selector would otherwise run. vs the std-grid entry a
            # TIE is a pass — at sizes both grids cover, the searches
            # can land the same optimal schedule shape, and the lat
            # window's deterministic priority breaks the tie
            if t_lat >= t_hand or t_lat > t_std:
                fails.append(
                    f"lat cell ({nbytes} B, w{world}): {key} predicted "
                    f"{t_lat * 1e6:.0f} us does not win (hand "
                    f"{t_hand * 1e6:.0f} us, std "
                    f"{t_std * 1e6:.0f} us)")
            # measured on THIS memcpy-wire mesh, reported unvarnished:
            # the mesh has no per-hop alpha, so the lat schedule's win
            # is a calibrated-link claim, not a local wall-clock one
            comp = ScheduleCompiler(mesh, use_pallas_ring=False)
            plan0 = select_algorithm(Operation.allreduce, count, 4,
                                     world, tuning=TuningParams.default(),
                                     **kw)
            opts = CallOptions(scenario=Operation.allreduce, count=count,
                               function=int(ReduceFunction.SUM),
                               data_type=DataType.float32)
            fn_lat = comp.lower(opts, plan_lat)
            fn_0 = comp.lower(opts, plan0)
            x = rng.integers(-50, 50, (world, count)).astype(np.float32)
            for _ in range(3):
                jax.block_until_ready(fn_lat(x))
                jax.block_until_ready(fn_0(x))
            m_lat, m_0 = [], []
            for _ in range(SERVE_GATE_LAT_ROUNDS):
                t0 = time.perf_counter()
                jax.block_until_ready(fn_lat(x))
                m_lat.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                jax.block_until_ready(fn_0(x))
                m_0.append(time.perf_counter() - t0)
            lat_cell.update(
                measured_lat_us=round(float(np.median(m_lat)) * 1e6, 1),
                measured_reg0_us=round(float(np.median(m_0)) * 1e6, 1),
                reg0_algorithm=plan0.algorithm.name)
            print(f"  lat cell {nbytes} B w{world}: {key} predicted "
                  f"{t_lat * 1e6:.0f} us vs hand {t_hand * 1e6:.0f} / "
                  f"std {t_std * 1e6:.0f} us; measured (memcpy mesh, "
                  f"unvarnished) lat {lat_cell['measured_lat_us']} us "
                  f"vs register-0 {lat_cell['measured_reg0_us']} us "
                  f"({plan0.algorithm.name})", file=sys.stderr)

    # 4. WAN leg (gated tail): the decode step's collective
    # fingerprint on the shaped 4-rank native world — 2 allreduces per
    # layer at B*d_model fp32, back to back, the alpha-bound regime
    wan_world = 4
    regime = {"ACCL_RT_WAN_ALPHA_US": "500", "ACCL_RT_WAN_GBPS": "1.0"}
    saved = {k: os.environ.get(k) for k in regime}
    os.environ.update(regime)
    try:
        w = EmuWorld(wan_world, transport="tcp",
                     max_eager=tnative.DEFAULT_MAX_EAGER,
                     rx_buf_bytes=tnative.DEFAULT_RX_BUF)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        n_ar = 2 * cfg.n_layers
        n = SERVE_GATE_BATCH * cfg.d_model

        def wan_body(rank, i):
            x = np.full(n, float(i + 1), np.float32)
            out = np.zeros(n, np.float32)
            for _ in range(n_ar):  # warm: sessions + buffer pools
                rank.allreduce(x.copy(), out, n, ReduceFunction.SUM)
            times = []
            for _ in range(SERVE_GATE_WAN_STEPS):
                t0 = time.perf_counter()
                for _ in range(n_ar):
                    rank.allreduce(x.copy(), out, n, ReduceFunction.SUM)
                times.append(time.perf_counter() - t0)
            return times

        wan_times = w.run(wan_body)[0]
    finally:
        w.close()
    wreg = MetricsRegistry()
    wh = wreg.histogram("accl_serve_wan_step_seconds", world=wan_world)
    for t in wan_times:
        wh.observe(t)
    wrow = wreg.snapshot()["histograms"][
        "accl_serve_wan_step_seconds"][0]
    wan_tail = {quantile_key(q): round(wrow[quantile_key(q)] * 1e3, 2)
                for q in (0.5, 0.99, 0.999)}
    if wrow["p99"] > SERVE_GATE_WAN_P99_CEILING_S:
        fails.append(f"shaped-WAN decode-step p99 {wrow['p99']:.3f} s "
                     f"over the {SERVE_GATE_WAN_P99_CEILING_S} s "
                     "ceiling")
    print(f"  shaped-WAN soak (w{wan_world}, {n_ar} x {n * 4} B "
          f"allreduce/step, {SERVE_GATE_WAN_STEPS} steps): p50 "
          f"{wan_tail['p50']} p99 {wan_tail['p99']} p99.9 "
          f"{wan_tail['p99_9']} ms/step", file=sys.stderr)

    verdict = {
        "metric": "serve gate: continuous-batching KV-decode over the "
                  f"fused one-dispatch step (w{world} mesh parity + "
                  "fused-vs-eager medians + calibrated lat-cell "
                  f"selection; shaped-WAN w{wan_world} soak tail)",
        "value": round(speedup, 2),
        "unit": "x fused vs eager decode step (interleaved medians)",
        "platform": "cpu-emulator",
        "parity": {"batched_eq_sequential": parity_seq,
                   "fused_eq_eager": parity_eager},
        "fused_ms_per_step": round(med_f * 1e3, 3),
        "eager_ms_per_step": round(med_e * 1e3, 3),
        "fused_speedup": round(speedup, 2),
        "fused_speedup_floor": SERVE_GATE_FUSED_SPEEDUP,
        "tokens_per_s": round(tokens_s, 1),
        "batch_slots": SERVE_GATE_BATCH,
        "step_tail_ms": {k: (round(v * 1e3, 3) if v is not None
                             else None) for k, v in tail.items()},
        "lat_cell": lat_cell,
        "wan_step_tail_ms": wan_tail,
        "wan_p99_ceiling_s": SERVE_GATE_WAN_P99_CEILING_S,
    }
    print(json.dumps(verdict))
    # committed artifact for tools/report_bench.py (same posture as
    # the other accl_log/ sources: latest run wins, absence reported)
    log_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "accl_log")
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, "serve_gate.json"), "w") as fh:
        json.dump({**verdict, "fails": list(fails)}, fh, indent=1)
        fh.write("\n")
    if fails:
        for f in fails:
            print(f"FAIL: {f}", file=sys.stderr)
        sys.exit(1)


def _tenant_gate_main():
    """bench.py --tenant-gate: see the TENANT_GATE_* constants block
    for the claims. stdout: ONE JSON line {metric, value = worst
    small-tenant mixed p99 over its solo baseline, band verdict,
    certification counters, bulk wire accounting, WFQ prefix share,
    SLO misses + noisy-neighbor attribution}."""
    import threading
    import types as _types

    import jax
    from jax.sharding import Mesh

    from accl_tpu import ReduceFunction
    from accl_tpu.accl import ACCL
    from accl_tpu.scheduler import SchedulerSaturatedError
    from accl_tpu.telemetry.metrics import MetricsRegistry

    fails = []
    world = min(len(jax.devices()), 8)
    mesh = Mesh(np.array(jax.devices()[:world]), axis_names=("ccl",))
    accl = ACCL(mesh)

    n_small = TENANT_GATE_SMALL_COUNT
    n_bulk = TENANT_GATE_BULK_CHUNK_ELEMS
    chunk_wire = 2 * (world - 1) * n_bulk * 4  # ring allreduce bytes
    n_chunks = math.ceil(TENANT_GATE_BULK_WIRE_BYTES / chunk_wire)

    # per-tenant arenas: every tenant compiles its own program over its
    # own buffers, so the admitted set is disjoint BY CONSTRUCTION and
    # the certifier's clean verdicts are real, not vacuous
    small = []
    for i in range(TENANT_GATE_SMALL_TENANTS):
        src = accl.create_buffer(n_small, np.float32)
        dst = accl.create_buffer(n_small, np.float32)
        src.write(np.full((world, n_small), float(i + 1), np.float32))
        seq = accl.sequence()
        seq.allreduce(src, dst, n_small, ReduceFunction.SUM)
        small.append((seq.compile(), dst))
    b_src = accl.create_buffer(n_bulk, np.float32)
    b_dst = accl.create_buffer(n_bulk, np.float32)
    b_src.write(np.ones((world, n_bulk), np.float32))
    bseq = accl.sequence()
    bseq.allreduce(b_src, b_dst, n_bulk, ReduceFunction.SUM)
    bulk_prog = bseq.compile()

    # warm every program once (the first dispatch pays the XLA compile)
    for p, _ in small:
        p.run()
    bulk_prog.run()

    # the physical head-of-line unit: one bulk chunk holds the launch
    # mutex for its whole XLA step, so its solo p50 is the allowance
    # the committed band budgets per TENANT_GATE_HOL_CHUNKS
    tb = []
    for _ in range(3):
        t0 = time.perf_counter()
        bulk_prog.run()
        tb.append(time.perf_counter() - t0)
    bulk_chunk_p50 = sorted(tb)[len(tb) // 2]

    # 1. SOLO baseline: one small tenant alone, through the SAME
    # scheduler path (admission + certification + metering included)
    reg_solo = MetricsRegistry()
    solo = accl.scheduler(capacity_s=1e9, registry=reg_solo)
    solo.register_tenant("solo", priority=0)
    solo.submit("solo", small[0][0], repeats=TENANT_GATE_WAVES)
    solo.drain()
    (srow,) = reg_solo.snapshot()["histograms"][
        "accl_tenant_dispatch_seconds"]
    solo_p99 = srow["p99"]
    print(f"  solo small-tenant baseline: p50 {srow['p50'] * 1e3:.2f} "
          f"p99 {solo_p99 * 1e3:.2f} ms over {srow['count']} "
          f"dispatches; bulk chunk p50 {bulk_chunk_p50 * 1e3:.0f} ms "
          f"({n_bulk * 4} B payload = {chunk_wire} wire B/chunk, "
          f"{n_chunks} chunks to the {TENANT_GATE_BULK_WIRE_BYTES} B "
          "budget)", file=sys.stderr)

    # 2. MIXED soak: the bulk tenant's whole wire budget queued up
    # front at priority 1; small tenants submit paced waves at
    # priority 0 while it drains. Workers loop step() directly —
    # drain() would return between waves.
    reg = MetricsRegistry()
    sched = accl.scheduler(capacity_s=1e9, registry=reg)
    for i in range(TENANT_GATE_SMALL_TENANTS):
        sched.register_tenant(f"t{i}", priority=0)
    sched.register_tenant("bulk", priority=1)
    sched.submit("bulk", bulk_prog, repeats=n_chunks)

    stop = threading.Event()

    def _worker():
        while not stop.is_set():
            if not sched.step():
                time.sleep(0.001)

    workers = [threading.Thread(target=_worker, daemon=True,
                                name=f"tenant-gate-{k}")
               for k in range(TENANT_GATE_WORKERS)]
    t_soak = time.perf_counter()
    for w in workers:
        w.start()
    for r in range(TENANT_GATE_WAVES):
        for i in range(TENANT_GATE_SMALL_TENANTS):
            sched.submit(f"t{i}", small[i][0])
        time.sleep(TENANT_GATE_WAVE_GAP_S)
    total = n_chunks + TENANT_GATE_WAVES * TENANT_GATE_SMALL_TENANTS
    deadline = time.perf_counter() + TENANT_GATE_SOAK_TIMEOUT_S
    while sched.stats["dispatches"] < total \
            and time.perf_counter() < deadline:
        time.sleep(0.05)
    stop.set()
    for w in workers:
        w.join(timeout=60)
    soak_s = time.perf_counter() - t_soak
    if sched.stats["dispatches"] < total:
        fails.append(f"soak stalled at {sched.stats['dispatches']}/"
                     f"{total} dispatches inside "
                     f"{TENANT_GATE_SOAK_TIMEOUT_S:g} s")
    if not (np.asarray(b_dst.host)[0] == world).all():
        fails.append("bulk allreduce result corrupted during the soak")
    if not (np.asarray(small[3][1].host)[0] == 4.0 * world).all():
        fails.append("small-tenant allreduce result corrupted during "
                     "the soak")

    stats = dict(sched.stats)
    rows = reg.snapshot()["histograms"]["accl_tenant_dispatch_seconds"]
    small_p99 = {r["labels"]["tenant"]: r["p99"] for r in rows
                 if r["labels"]["tenant"] != "bulk"}
    worst_tenant, worst_p99 = max(small_p99.items(),
                                  key=lambda kv: kv[1])
    band_s = solo_p99 * TENANT_GATE_P99_BAND \
        + TENANT_GATE_HOL_CHUNKS * bulk_chunk_p50
    print(f"  mixed soak ({soak_s:.1f} s, {stats['dispatches']} "
          f"dispatches, {stats['concurrent_dispatches']} concurrent): "
          f"worst small p99 {worst_p99 * 1e3:.1f} ms ({worst_tenant}) "
          f"vs band {band_s * 1e3:.1f} ms", file=sys.stderr)
    if worst_p99 > band_s:
        fails.append(
            f"small-tenant p99 left the committed band: {worst_tenant} "
            f"p99 {worst_p99 * 1e3:.1f} ms > {band_s * 1e3:.1f} ms "
            f"(solo {solo_p99 * 1e3:.2f} ms x {TENANT_GATE_P99_BAND:g}"
            f" + {TENANT_GATE_HOL_CHUNKS:g} bulk chunks)")
    if stats["uncertified_concurrent"] != 0:
        fails.append(f"{stats['uncertified_concurrent']} concurrent "
                     "dispatches ran WITHOUT a certificate")
    if stats["concurrent_dispatches"] < 1:
        fails.append("the soak never overlapped two certified "
                     "programs (concurrent_dispatches == 0)")
    if stats["certified_concurrent"] != stats["concurrent_dispatches"]:
        fails.append(
            f"certified_concurrent {stats['certified_concurrent']} != "
            f"concurrent_dispatches {stats['concurrent_dispatches']}")
    missing = [f"t{i}" for i, (p, _) in enumerate(small)
               if p.certificate is None]
    if bulk_prog.certificate is None:
        missing.append("bulk")
    if missing:
        fails.append("programs dispatched without a certificate id: "
                     + ", ".join(missing))
    bulk_disp = sched.tenants.get("bulk").account()["dispatched"]
    wire_moved = bulk_disp * chunk_wire
    if wire_moved < TENANT_GATE_BULK_WIRE_BYTES:
        fails.append(f"bulk tenant moved {wire_moved} wire bytes < "
                     f"the {TENANT_GATE_BULK_WIRE_BYTES} B budget")

    # 3. WFQ prefix share (deterministic, pinned unit costs): 4:1
    # weights with the light tenant submitted FIRST -> the heavy
    # tenant owns 8 of the first 10 dispatches, exactly its weight
    # share. No wall clock in this sub-check.
    order = []
    fair = accl.scheduler(capacity_s=1e9, registry=MetricsRegistry())
    fair.register_tenant("heavy", priority=5, weight=4.0)
    fair.register_tenant("light", priority=5, weight=1.0)

    def _pinned(tag):
        p = _types.SimpleNamespace(
            footprint=None, signature=None,
            _prepared=_types.SimpleNamespace(
                cert=None, desc=_types.SimpleNamespace(steps=[])))
        p.run = lambda **kw: order.append(tag)
        return p

    fair.submit("light", _pinned("light"), repeats=8, cost_s=1.0)
    fair.submit("heavy", _pinned("heavy"), repeats=8, cost_s=1.0)
    for _ in range(10):
        fair.step()
    share = order[:10].count("heavy") / 10.0
    want = 4.0 / (4.0 + 1.0)
    print(f"  WFQ first-10 prefix: heavy share {share:.2f} "
          f"(want {want:.2f} +- {TENANT_GATE_FAIR_SHARE_TOL:g})",
          file=sys.stderr)
    if abs(share - want) > TENANT_GATE_FAIR_SHARE_TOL:
        fails.append(f"WFQ first-10 heavy share {share:.2f} off the "
                     f"4:1 weight split {want:.2f} (tol "
                     f"{TENANT_GATE_FAIR_SHARE_TOL:g})")

    # 4. saturation stays a TYPED error (never a silent drop)
    bp = accl.scheduler(capacity_s=1e-6, registry=MetricsRegistry())
    bp.register_tenant("bp")
    try:
        bp.submit("bp", _pinned("bp"), cost_s=1.0)
        fails.append("saturated submit did not raise "
                     "SchedulerSaturatedError")
    except SchedulerSaturatedError:
        pass

    slo_misses = {name: sched.tenants.get(name).account()["slo_misses"]
                  for name in sched.tenants.names()}
    ratio = worst_p99 / max(solo_p99, 1e-9)
    verdict = {
        "metric": f"tenant gate: {TENANT_GATE_SMALL_TENANTS} "
                  "interactive tenants + 1 bulk tenant "
                  f"({n_chunks} x {n_bulk * 4} B chunks = "
                  f"{n_chunks * chunk_wire} ring-wire bytes) over the "
                  f"certified concurrent scheduler (w{world} mesh)",
        "value": round(ratio, 2),
        "unit": "x small-tenant p99, mixed soak vs solo baseline",
        "platform": "cpu-emulator",
        "small_p99_solo_ms": round(solo_p99 * 1e3, 3),
        "small_p99_mixed_ms": {t: round(v * 1e3, 3)
                               for t, v in sorted(small_p99.items())},
        "worst": {"tenant": worst_tenant,
                  "p99_ms": round(worst_p99 * 1e3, 3),
                  "band_ms": round(band_s * 1e3, 3)},
        "band": {"p99_band": TENANT_GATE_P99_BAND,
                 "hol_chunks": TENANT_GATE_HOL_CHUNKS,
                 "bulk_chunk_p50_ms": round(bulk_chunk_p50 * 1e3, 1)},
        "bulk": {"chunks": bulk_disp, "chunk_elems": n_bulk,
                 "wire_bytes": wire_moved,
                 "wire_budget": TENANT_GATE_BULK_WIRE_BYTES},
        "stats": stats,
        "soak_s": round(soak_s, 1),
        "wfq": {"first10_heavy_share": share, "want": want,
                "tol": TENANT_GATE_FAIR_SHARE_TOL},
        "slo_misses": slo_misses,
        "noisy_neighbors": sched.noisy_neighbor_report(),
        "certificate": bulk_prog.certificate,
    }
    print(json.dumps(verdict))
    log_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "accl_log")
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, "tenant_gate.json"), "w") as fh:
        json.dump({**verdict, "fails": list(fails)}, fh, indent=1)
        fh.write("\n")
    if fails:
        for f in fails:
            print(f"FAIL: {f}", file=sys.stderr)
        sys.exit(1)


def _hier_run_composed(locals_, outers, pods, inner, nbytes, iters,
                       stripes=1, check=None):
    """Drive the composed two-tier allreduce on the native emulated
    world: per logical rank (pod p, inner position i) the phase chain
    is inner reduce-scatter on the pod's local-POE world, allreduce of
    the 1/L shard on inner position i's cross-pod TCP world, inner
    allgather — so only 1/L of the payload ever crosses the slow tier,
    the HiCCL composition the XLA-tier HIER_RS_AR_AG plan lowers.
    Returns wall seconds per iteration (all ranks synchronized through
    the collectives themselves). `check` (rank-indexed inputs) verifies
    every rank's result against the numpy oracle bitwise."""
    import threading

    from accl_tpu import ReduceFunction

    n = nbytes // 4
    assert n % (inner * pods * max(stripes, 1)) == 0
    world = pods * inner
    barrier = threading.Barrier(world + 1)
    errs: list[Exception] = []

    def body(p, i):
        g = p * inner + i  # outer-major global rank (RankMap convention)
        loc = locals_[p].ranks[i]
        out = outers[i].ranks[p]
        x = (check[g] if check is not None
             else np.ones(n, np.float32))
        full = np.zeros(n, np.float32)
        per = n // max(stripes, 1)
        shard = np.zeros(per // inner, np.float32)
        red = np.zeros(per // inner, np.float32)
        try:
            barrier.wait()
            for _ in range(iters):
                for s in range(max(stripes, 1)):
                    seg = x[s * per:(s + 1) * per]
                    loc.reduce_scatter(seg, shard, per // inner,
                                       ReduceFunction.SUM)
                    out.allreduce(shard, red, per // inner,
                                  ReduceFunction.SUM)
                    loc.allgather(red, full[s * per:(s + 1) * per],
                                  per // inner)
            if check is not None:
                want = np.sum(check, axis=0)
                assert np.array_equal(full, want), \
                    f"hier composed result wrong on rank {g}"
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=body, args=(p, i))
               for p in range(pods) for i in range(inner)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    sec = (time.perf_counter() - t0) / iters
    if errs:
        raise errs[0]
    return sec


def _hier_run_flat(flat, nbytes, iters, check=None):
    """The flat baseline on the same emulated 2-tier world: a plain
    allreduce on the all-ranks TCP world, where EVERY ring hop crosses
    the slow tier (the pre-hierarchy state of the repo)."""
    import threading

    from accl_tpu import ReduceFunction

    n = nbytes // 4
    world = len(flat.ranks)
    barrier = threading.Barrier(world + 1)
    errs: list[Exception] = []

    def body(g):
        x = (check[g] if check is not None
             else np.ones(n, np.float32))
        out = np.zeros(n, np.float32)
        try:
            barrier.wait()
            for _ in range(iters):
                flat.ranks[g].allreduce(x, out, n, ReduceFunction.SUM)
            if check is not None:
                assert np.array_equal(out, np.sum(check, axis=0)), \
                    f"flat result wrong on rank {g}"
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=body, args=(g,))
               for g in range(world)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    sec = (time.perf_counter() - t0) / iters
    if errs:
        raise errs[0]
    return sec


def _hier_gate_main():
    """bench.py --hier-gate: the emulated 2-tier world (8 ranks as 4
    pods x 2: intra-pod local-POE inner tier, cross-pod TCP outer tier)
    where the hierarchical allreduce claim is MEASURED, not asserted:

      1. run the composed two-tier allreduce (inner RS -> outer shard
         AR -> inner AG, numerically verified against the numpy oracle)
         and the flat all-TCP allreduce at each payload size, wall
         clock per iteration
      2. drain every world's device trace ring into tier-tagged SPAN v1
         events (args["tier"] = "inner" for the local-POE pods,
         "outer" for the TCP groups) and refit EACH TIER'S LinkParams
         independently (telemetry.feedback.calibrate_tiers_from_trace)
      3. gate: at >= 1 size the hierarchical composition must beat the
         flat ring in BOTH measured wall time AND the per-tier
         prediction (timing.predict_tiered under the refit TierLinks
         vs the flat plan charged to the outer link), and the refit
         calibration must open the HIER_ALLREDUCE_MIN_COUNT crossover
         window (timing.tuning_crossovers hier_allreduce_min_bytes > 0)
      4. write the per-tier fit into accl_log/timing_model.json
         ("link_tiers": the calibration ACCL.autotune and bench --check
         read back through telemetry.feedback.default_tier_links) and
         the tier-tagged trace to accl_log/hier_trace.json

    stdout: ONE JSON line {metric, value = best measured hier-vs-flat
    speedup, predicted ratio, per-size table, refit tier links}."""
    from accl_tpu.constants import (
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        Operation,
        TuningParams,
    )
    from accl_tpu.device.emu_device import EmuWorld
    from accl_tpu.sequencer.plan import (
        Algorithm,
        Plan,
        Protocol,
        select_algorithm,
    )
    from accl_tpu.sequencer.timing import (
        best_stripes,
        predict,
        predict_tiered,
        tuning_crossovers,
    )
    from accl_tpu.telemetry import (
        calibrate_tiers_from_trace,
        default_link,
        get_tracer,
        validate_trace,
        write_trace,
    )
    from accl_tpu.telemetry import native as tnative

    pods, inner = 4, 2
    world = pods * inner
    sizes = (64 * 1024, 1024 * 1024)
    iters = 4
    rng = np.random.default_rng(42)

    # the outer tier is a SHAPED wire: loopback TCP is as fast as the
    # local POE (it is the same host's memory system), so without a
    # link model the "2-tier" world would be flat and the measured leg
    # meaningless. ACCL_RT_WAN_* (native frame_out, charged per frame
    # inside the per-peer tx lock) gives the TCP groups a DCN-class
    # link; the local-POE pods stay unshaped — they ARE the fast tier.
    # DCN-class shaping: alpha FAR above the local POE's intrinsic
    # per-segment cost (~150-350 us sequencer parking on the CI host,
    # which is CPU-share throttled and noisy), so the two tiers are
    # genuinely asymmetric the way ICI/DCN are AND the composition's
    # slow-tier byte/message reduction dwarfs host jitter — the gate
    # measures the tier asymmetry, not scheduler luck
    wan_alpha_us, wan_gbps = 2000, 0.125
    saved = {k: os.environ.get(k) for k in
             ("ACCL_RT_TRACE", "ACCL_RT_WAN_ALPHA_US",
              "ACCL_RT_WAN_GBPS")}
    os.environ["ACCL_RT_TRACE"] = "1"
    wkw = dict(max_eager=tnative.DEFAULT_MAX_EAGER,
               rx_buf_bytes=tnative.DEFAULT_RX_BUF)
    try:
        # 4 intra-pod local-POE worlds (the ICI analog), one cross-pod
        # TCP world per inner position (the DCN analog: inner position
        # i's shards allreduce across pods on outers[i]), and the flat
        # all-TCP baseline world (every hop crosses the shaped wire —
        # exactly the flat ring's position on real two-tier hardware)
        locals_ = [EmuWorld(inner, transport="local", **wkw)
                   for _ in range(pods)]
        os.environ["ACCL_RT_WAN_ALPHA_US"] = str(wan_alpha_us)
        os.environ["ACCL_RT_WAN_GBPS"] = str(wan_gbps)
        outers = [EmuWorld(pods, transport="tcp", **wkw)
                  for _ in range(inner)]
        flat = EmuWorld(world, transport="tcp", **wkw)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    per_size = []
    try:
        # correctness first: composed result == flat result == oracle,
        # bitwise, on integer payloads (striped variant included)
        ncheck = world * pods * 8
        check = rng.integers(-50, 50,
                             (world, ncheck)).astype(np.float32)
        _hier_run_composed(locals_, outers, pods, inner, ncheck * 4, 1,
                           check=check)
        _hier_run_composed(locals_, outers, pods, inner, ncheck * 4, 1,
                           stripes=2, check=check)
        _hier_run_flat(flat, ncheck * 4, 1, check=check)

        # Calibration runs FIRST, per tier IN ISOLATION: inside the
        # composed pipeline an inner span absorbs its partner's outer
        # wait (cross-tier skew), which would contaminate the fit —
        # and the refit must exist BEFORE the measured legs so the
        # composed run can use the stripe count the cost model
        # actually picks (the gate must measure the same plan the
        # prediction scores and the register enables). Discard the
        # correctness traffic's spans, run each tier's own lockstep
        # sweep, and fit from only those.
        for w in locals_ + outers + [flat]:
            for r in w.ranks:
                r.trace_read()

        from accl_tpu import ReduceFunction

        def _cal_inner(rank, _i):
            for nbytes in (16 * 1024, 128 * 1024, 512 * 1024):
                n = nbytes // 4
                x = np.ones(n, np.float32)
                shard = np.zeros(n // inner, np.float32)
                full = np.zeros(n, np.float32)
                for _ in range(2):
                    rank.reduce_scatter(x, shard, n // inner,
                                        ReduceFunction.SUM)
                    rank.allgather(shard, full, n // inner)

        def _cal_outer(rank, _i):
            for nbytes in (16 * 1024, 128 * 1024, 512 * 1024):
                n = nbytes // 4
                x = np.ones(n, np.float32)
                out = np.zeros(n, np.float32)
                for _ in range(2):
                    rank.allreduce(x, out, n, ReduceFunction.SUM)

        for w in locals_:
            w.run(_cal_inner)
        for w in outers:
            w.run(_cal_outer)

        # drain every world with its tier label; the flat world's spans
        # stay untagged (they belong to neither tier's link)
        tr = get_tracer()
        tr.enable()
        link = default_link()
        dropped = 0
        for p, w in enumerate(locals_):
            _, d = tnative.drain_world(w, link=link, tracer=tr,
                                       tier="inner",
                                       track_prefix=f"hier_pod{p}")
            dropped += d
        for i, w in enumerate(outers):
            _, d = tnative.drain_world(w, link=link, tracer=tr,
                                       tier="outer",
                                       track_prefix=f"hier_dcn{i}")
            dropped += d
        _, d = tnative.drain_world(flat, link=link, tracer=tr,
                                   track_prefix="hier_flat")
        dropped += d

        trace = tr.to_trace({"world": world, "pods": pods,
                             "inner": inner,
                             "native_dropped": dropped,
                             "cost_shape": "aggregate"})
        validate_trace(trace)
        tiers = calibrate_tiers_from_trace(trace)
        print(f"  tier refit: inner alpha "
              f"{tiers.inner.alpha * 1e6:.1f} us beta "
              f"{tiers.inner.beta / 1e9:.2f} GB/s / outer alpha "
              f"{tiers.outer.alpha * 1e6:.1f} us beta "
              f"{tiers.outer.beta / 1e9:.3f} GB/s", file=sys.stderr)

        # measured + predicted legs per size, SAME plan on both: the
        # composed run executes the stripe count the cost model picks
        # under the refit calibration (predicting a pipelined plan the
        # gate never measured would compare two different algorithms),
        # and the prediction uses the aggregate cost shape the spans
        # were fitted in; the flat side is charged to the outer link.
        kw = dict(max_eager_size=DEFAULT_MAX_EAGER_SIZE,
                  eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE)
        for nbytes in sizes:
            cnt = nbytes // 4
            s = best_stripes(tiers, cnt, 4, inner, pods,
                             aggregate=True)
            hplan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG, cnt,
                         1, inner_world=inner, outer_world=pods,
                         stripes=s)
            t_h = predict_tiered(tiers, hplan, cnt, 4, aggregate=True)
            fplan = select_algorithm(Operation.allreduce, cnt, 4,
                                     world,
                                     tuning=TuningParams.default(),
                                     **kw)
            t_f = predict(tiers.outer, Operation.allreduce, fplan, cnt,
                          4, world,
                          rx_buf_bytes=DEFAULT_EAGER_RX_BUF_SIZE,
                          aggregate=True)
            # warm (TCP session establishment, buffer pools), then
            # time INTERLEAVED — one composed run and one flat run per
            # round, median across rounds, so a transient load burst
            # (this container is CPU-share throttled) lands on both
            # sides of the gate ratio instead of poisoning one
            _hier_run_composed(locals_, outers, pods, inner, nbytes, 1,
                               stripes=s)
            _hier_run_flat(flat, nbytes, 1)
            th, tf = [], []
            for _ in range(iters):
                th.append(_hier_run_composed(locals_, outers, pods,
                                             inner, nbytes, 1,
                                             stripes=s))
                tf.append(_hier_run_flat(flat, nbytes, 1))
            t_hier = float(np.median(th))
            t_flat = float(np.median(tf))
            per_size.append({"bytes": nbytes, "stripes": s,
                             "hier_s": t_hier, "flat_s": t_flat,
                             "measured_ratio": t_flat / t_hier,
                             "predicted_hier_s": t_h,
                             "predicted_flat_s": t_f,
                             "predicted_ratio": t_f / t_h})
            print(f"  hier {nbytes:>8d} B (S={s}): composed "
                  f"{t_hier * 1e6:9.1f} us vs flat TCP ring "
                  f"{t_flat * 1e6:9.1f} us ({t_flat / t_hier:5.2f}x "
                  f"measured, {t_f / t_h:5.2f}x predicted)",
                  file=sys.stderr)
    finally:
        for w in locals_ + outers + [flat]:
            w.close()

    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    write_trace(outdir / "hier_trace.json", trace)

    # the crossover the registers are set from must open under the
    # refit calibration (the measured-selection posture: autotune can
    # only turn the composition on because THIS calibration says it wins)
    cross = tuning_crossovers(tiers.outer, world=world,
                              tier_links=tiers,
                              topology=(inner, pods))
    hier_window = cross["hier_allreduce_min_bytes"]
    print(f"  hier crossover window: >= {hier_window} B",
          file=sys.stderr)

    # The pod-scale synthesis leg (ROADMAP item 3): under THIS run's
    # refit per-tier calibration — the emulated 2-tier world's own
    # measured links — a committed tiered library entry must beat the
    # hand-written striped composition (best stripe count per size, the
    # strongest hand-written two-tier opponent) at >= 1 size. Scored in
    # the aggregate shape the spans were fitted in, the same posture as
    # the measured/predicted legs above; the measured-on-mesh twin is
    # bench --check's allreduce_synth_tier cell.
    from accl_tpu.sequencer import synthesis as _synth

    synth_tier_rows = []
    for nbytes in sizes:
        cnt = nbytes // 4
        key = _synth.select_entry(Operation.allreduce, world, nbytes,
                                  tiers=(inner, pods))
        if key is None:
            synth_tier_rows.append({"bytes": nbytes, "entry": None})
            continue
        spec = _synth.entry_for_key(key).spec
        t_st = _synth.predict_spec_tiered(tiers, spec, cnt, 4,
                                          aggregate=True)
        s_h = best_stripes(tiers, cnt, 4, inner, pods, aggregate=True)
        hplan = Plan(Protocol.EAGER, Algorithm.HIER_RS_AR_AG, cnt, 1,
                     inner_world=inner, outer_world=pods, stripes=s_h)
        t_hw = predict_tiered(tiers, hplan, cnt, 4, aggregate=True)
        synth_tier_rows.append({
            "bytes": nbytes, "entry": key,
            "predicted_synth_s": t_st,
            "predicted_hand_striped_s": t_hw,
            "predicted_ratio": t_hw / t_st})
        print(f"  synth-tier {nbytes:>8d} B: {key} "
              f"{t_st * 1e6:9.1f} us vs striped composition "
              f"{t_hw * 1e6:9.1f} us ({t_hw / t_st:5.2f}x predicted "
              "under the refit tier links)", file=sys.stderr)

    # persist the per-tier fit for default_tier_links consumers
    # (ACCL.autotune, bench --check's hier cell, plan stripe selection)
    model_path = outdir / "timing_model.json"
    model = json.loads(model_path.read_text()) if model_path.exists() \
        else {}
    model["link_tiers"] = {
        "source": "bench.py --hier-gate (emulated 2-tier world: "
                  f"{pods} local-POE pods x {inner}, TCP outer)",
        "inner": {"alpha_us": tiers.inner.alpha * 1e6,
                  "beta_gbps": tiers.inner.beta / 1e9},
        "outer": {"alpha_us": tiers.outer.alpha * 1e6,
                  "beta_gbps": tiers.outer.beta / 1e9},
    }
    model_path.write_text(json.dumps(model, indent=1, sort_keys=True)
                          + "\n")

    wins = [r for r in per_size
            if r["measured_ratio"] > 1.0 and r["predicted_ratio"] > 1.0]
    best = max((r["measured_ratio"] for r in per_size), default=0.0)
    synth_wins = [r for r in synth_tier_rows
                  if r.get("predicted_ratio", 0.0) > 1.0]
    print(json.dumps({
        "metric": "hierarchical allreduce vs flat TCP ring, emulated "
                  f"2-tier world ({pods} pods x {inner}, local POE "
                  "inner + TCP outer): best measured speedup",
        "value": round(best, 3),
        "unit": "x",
        "platform": "cpu-emulator",
        "sizes": per_size,
        "hier_crossover_min_bytes": hier_window,
        "tier_links": model["link_tiers"],
        "synth_tier": synth_tier_rows,
    }))
    if not wins:
        print("FAIL: hierarchical allreduce beat the flat ring at NO "
              "size in both measured and predicted time — the "
              "composition claim does not hold on this world",
              file=sys.stderr)
        sys.exit(1)
    if hier_window <= 0:
        print("FAIL: refit per-tier calibration does not open the "
              "HIER_ALLREDUCE_MIN_COUNT window (hier never predicts "
              "faster than flat) — autotune could never enable the "
              "composition", file=sys.stderr)
        sys.exit(1)
    if not synth_wins:
        print("FAIL: no committed tiered synthesized entry beats the "
              "hand-written striped composition at any size under "
              "THIS world's refit per-tier calibration — the "
              "pod-scale synthesis claim does not hold (re-run "
              "tools/accl_synth.py --export --tiers "
              f"{inner}x{pods} if the calibration legitimately moved)",
              file=sys.stderr)
        sys.exit(1)


def _smoke_main():
    """bench.py --smoke: the CI-facing quick lane — runs the fused-vs-
    eager sequence benchmark on the virtual CPU mesh and emits ONE JSON
    line whose value is the speedup, so per-PR regressions in the fused
    path are visible without the full sweep. Also gates the sequence
    linter's overhead: the static analysis stage must cost <5% of the
    record+compile time it fronts."""
    import jax

    world = min(len(jax.devices()), 4)
    rows, speedup = bench_sequence(jax, world)
    lint_sec, rc_sec, lint_ratio = measure_lint_overhead(jax, world)
    rows.append(("sequence_lint_overhead", 0, lint_sec, lint_ratio,
                 1.0, True))
    print(f"  lint stage {lint_sec*1e6:8.1f} us vs record+compile "
          f"{rc_sec*1e3:8.1f} ms ({lint_ratio*100:.3f}%)",
          file=sys.stderr)
    intf_sec, intf_rc, intf_ratio = measure_interference_overhead(jax, world)
    rows.append(("interference_footprint_overhead", 0, intf_sec,
                 intf_ratio, 1.0, True))
    print(f"  footprint+certify {intf_sec*1e6:8.1f} us vs record+compile "
          f"{intf_rc*1e3:8.1f} ms ({intf_ratio*100:.3f}%)",
          file=sys.stderr)
    # disabled-telemetry overhead against the fused chain this very run
    # measured — instrumentation must be free when off (shared gate:
    # telemetry_disabled_gate, same constants as bench.py --trace)
    sec_fused = next(s for t, b, s, *_ in rows if "fused" in t)
    tel_site, tel_ratio, tel_ok = telemetry_disabled_gate(sec_fused)
    rows.append(("telemetry_disabled_overhead", 0, tel_site, tel_ratio,
                 1.0, True))
    print(f"  telemetry disabled-path {tel_site*1e9:6.0f} ns/site "
          f"({tel_ratio*100:.4f}% of fused chain)", file=sys.stderr)
    q_reduction, q_max_rel = bench_quantized_wire(jax, world)
    rows.append(("quantized_allreduce_wire_reduction", 16 * 1024 * 1024,
                 0.0, q_reduction, 1.0, True))
    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    with open(outdir / "profile_smoke.csv", "w") as f:
        f.write("Test,Bytes,Seconds,Value,Regime\n")
        for t, b, s, g, _snr, _res in rows:
            f.write(f"{t},{b},{s:.6e},{g:.3f},smoke\n")
    print(json.dumps({
        "metric": "sequence_fused_vs_eager speedup, 3-collective chain "
                  f"(w{world}, one dispatch vs three)",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),  # eager chain = 1.0
        # quantized-wire gate lane: measured ppermute bytes-on-wire
        # reduction at 16 MiB (must hold >= 1.9x vs fp32) and the max
        # relative error of the int8-wire allreduce vs the fp32 oracle
        "quantized_wire_reduction": round(q_reduction, 2),
        "quantized_max_rel_error": round(q_max_rel, 6),
    }))
    # wire-byte gate: the quantized lanes exist to beat the 2x cast
    # ceiling — anything under 1.9x at 16 MiB means the scale
    # side-channel (or a regression) ate the win
    if q_reduction < 1.9:
        print(f"FAIL: quantized allreduce wire reduction "
              f"{q_reduction:.2f}x < 1.9x at 16 MiB", file=sys.stderr)
        sys.exit(1)
    # the gate is real: a fused path SLOWER than eager back-to-back
    # dispatch is a regression in the one property the sequence layer
    # exists for — fail the CI job, don't just log a number
    if speedup < 1.0:
        print(f"FAIL: fused sequence slower than eager ({speedup:.2f}x)",
              file=sys.stderr)
        sys.exit(1)
    if speedup < 1.15:
        print(f"WARN: fused speedup {speedup:.2f}x below the 1.15x target",
              file=sys.stderr)
    # the lint gate is real too: the static analyzer fronts every
    # recorded batch, so its cost must stay invisible against the
    # record+compile it guards (<5%, measured on this very run)
    if lint_ratio >= 0.05:
        print(f"FAIL: lint stage costs {lint_ratio*100:.1f}% of "
              "record+compile time (>= 5% budget)", file=sys.stderr)
        sys.exit(1)
    # ... and so must the cross-program footprint layer: extraction
    # rides every prepare_sequence and the pairwise certify fronts
    # multi-tenant admission (same 5% budget as the lint stage)
    if intf_ratio >= 0.05:
        print(f"FAIL: footprint extraction + pairwise certify costs "
              f"{intf_ratio*100:.1f}% of record+compile time "
              "(>= 5% budget)", file=sys.stderr)
        sys.exit(1)
    # the telemetry gate: the disabled tracing path fronts EVERY facade
    # call, so its cost must stay invisible (shared budget with --trace)
    if not tel_ok:
        print(f"FAIL: disabled telemetry costs {tel_ratio*100:.2f}% of "
              f"the fused chain (>= {TELEMETRY_OVERHEAD_BUDGET*100:.0f}% "
              "budget)", file=sys.stderr)
        sys.exit(1)


BASELINE_BENCH = pathlib.Path(__file__).parent / "BASELINE_BENCH.json"


def _shipped_link():
    """LinkParams from the committed calibrated timing model — delegates
    to synthesis.shipped_link so the model path and resolution rule live
    in ONE place (bench --check and --verify-library can never read
    different files)."""
    from accl_tpu.sequencer.synthesis import shipped_link

    return shipped_link()


def _decode_harness(jax, world):
    """The decode-step cell pair for bench --check: the fused
    one-dispatch KV-cache decode step (29 descriptors for the 4-layer
    serve-gate model: 7/layer + logits head) and its dispatch-per-layer
    eager twin, same model, same buffers layout, steady-state serving
    convention (fixed mid-context position, caches device-resident).
    Returns {"step": fn(mode), "nbytes": per-allreduce payload}."""
    from jax.sharding import Mesh

    from accl_tpu.accl import ACCL
    from accl_tpu.models import transformer as trf

    cfg = _serve_gate_cfg(trf)
    batch, max_len = SERVE_GATE_BATCH, SERVE_GATE_MAX_LEN
    params = jax.tree.map(np.asarray,
                          trf.init_params(cfg, jax.random.key(0)))
    mesh = Mesh(np.array(jax.devices()[:world]), axis_names=("ccl",))
    accl_f = ACCL(mesh)
    prog, bf = trf.make_decode_step_program(accl_f, cfg, params,
                                            batch=batch, max_len=max_len)
    accl_e = ACCL(mesh)
    be = trf.create_decode_buffers(accl_e, cfg, batch, max_len)
    trf.register_decode_consumers(accl_e, cfg, params, be.dims)
    rng = np.random.default_rng(29)
    toks = rng.integers(1, cfg.vocab, batch)
    pos = np.full(batch, max_len // 2, np.int64)

    def step(mode):
        if mode == "fused":
            trf.write_decode_inputs(bf, params, toks, pos)
            prog.run(to_device=True)
            return trf.read_decode_logits(bf, sync=True)
        trf.write_decode_inputs(be, params, toks, pos)
        trf.run_decode_step_eager(accl_e, cfg, be)
        return trf.read_decode_logits(be)

    return {"step": step, "nbytes": batch * cfg.d_model * 4}


def _check_sections(jax):
    """Measure the committed per-(section, size, world) baseline cells
    on the virtual CPU mesh: each section is one compiled collective
    program (hand-written vs synthesized where a library entry serves
    the cell). All cells are compiled and warmed first, then timed
    INTERLEAVED — one dispatch per cell per round, median across
    rounds — so a transient load burst lands on both sides of every
    speedup-gate ratio instead of poisoning whichever cell it happened
    to coincide with (sequential per-cell timing made the CI gate
    load-flaky). Returns (rows, world) where rows[section_id] =
    {seconds, messages, bytes, algorithm} and messages/bytes are the
    timing-model critical-path coefficients of the plan that actually
    ran (the refit samples)."""
    from jax.sharding import Mesh

    from accl_tpu.constants import (
        DEFAULT_EAGER_RX_BUF_SIZE,
        DEFAULT_MAX_EAGER_SIZE,
        DataType,
        Operation,
        ReduceFunction,
        TuningParams,
    )
    from accl_tpu.descriptor import CallOptions
    from accl_tpu.sequencer.lowering import ScheduleCompiler
    from accl_tpu.sequencer.plan import Algorithm, select_algorithm
    from accl_tpu.sequencer.timing import coefficients, tuning_crossovers

    world = min(len(jax.devices()), 8)
    mesh = Mesh(np.array(jax.devices()[:world]), axis_names=("ccl",))
    comp = ScheduleCompiler(mesh, use_pallas_ring=False)

    # synth registers from the SHIPPED calibrated link (the autotune
    # path): selection at the synthesized cells must come from measured
    # crossovers, not a hand-set override
    link = _shipped_link()
    tuning_synth = TuningParams.from_crossovers(
        tuning_crossovers(link, world=world))
    tuning_hand = TuningParams.default()
    # hier register from the SHIPPED per-tier calibration (written by
    # bench.py --hier-gate's native 2-tier refit) + the virtual 4x2
    # factoring of this flat mesh — the same measured-selection path
    # ACCL.autotune takes on a device that declares a topology
    from accl_tpu.telemetry.feedback import default_tier_links

    hier_topo = (2, 4)  # 8 ranks as 4 pods x 2 (inner_world, outer_world)
    tiers = default_tier_links()
    if tiers is None:
        raise SystemExit(
            "FAIL: timing model carries no link_tiers — run "
            "bench.py --hier-gate to calibrate the two-tier world")
    cross_hier = tuning_crossovers(link, world=world, tier_links=tiers,
                                   topology=hier_topo)
    tuning_hier = TuningParams.from_crossovers(cross_hier)
    if tuning_hier.hier_allreduce_min_count == 0:
        # distinguish the two ways the register can be off, or the
        # hier cell below fails with a confusing selection error: a
        # closed crossover means re-calibrate; a window start above
        # from_crossovers' register cap means the MIN was clamped to
        # OFF (the conservative clamp for a minimum threshold)
        raw = int(cross_hier["hier_allreduce_min_bytes"])
        why = ("the calibrated window starts at "
               f"{raw} B, above the register cap — clamped OFF"
               if raw > 0 else
               "the calibration predicts no hier-beats-flat suffix")
        raise SystemExit(
            f"FAIL: hier cell unavailable: {why}; re-run "
            "bench.py --hier-gate (and --write-baseline if the window "
            "legitimately moved)")
    kw = dict(max_eager_size=DEFAULT_MAX_EAGER_SIZE,
              eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE)

    # THE one cell table: section ids, the --write-baseline speedup
    # gates, and the refit-agreement checks are all derived from it (a
    # gate pairs a cell against a named slow twin; a retuned cell can't
    # silently orphan a gate or a refit check). `expect` pins what the
    # measured crossovers must select; `rounds`/`warm` bound the
    # dispatch count for heavy cells (the flat segmented ring at the
    # hier cell's payload re-dispatches per 4 KiB segment — the exact
    # pathology the hierarchical composition routes around — so its
    # cell costs ~1.2 s per dispatch and its 10x gate margin does not
    # need 40 rounds of noise suppression). The synth cells stay in the
    # small-payload regime, where per-dispatch hop latency dominates:
    # that is the region the synthesized schedules target AND the
    # region where the alpha-beta model's jumbo-stream story
    # approximates this mesh (see timing.coefficients); the hier pair
    # sits at the bottom of the calibrated HIER_ALLREDUCE_MIN_COUNT
    # window, where the two-tier claim is actually made.
    # floor at 512 KiB: inside every calibration's window we have
    # observed (the refit min flaps between 64 KiB and 512 KiB across
    # hosts), so the cell's payload — and with it the committed
    # baseline section id — stays put across re-calibrations unless
    # the window genuinely moves above it (then the cell follows the
    # window and the baseline is re-written deliberately)
    hier_nb = max(tuning_hier.hier_allreduce_min_count, 1 << 19)
    # the tiered synthesized cell needs a committed library entry for
    # this factoring whose window covers the hier cell's payload, and
    # the in-window arbitration must actually pick it at that payload
    # under the shipped per-tier calibration — both are selection
    # preconditions like the register checks above
    from accl_tpu.sequencer import synthesis as _synth_mod

    if _synth_mod.select_entry(Operation.allreduce, world, hier_nb,
                               tiers=hier_topo) is None:
        raise SystemExit(
            f"FAIL: allreduce_synth_tier cell unavailable: no "
            f"committed tiered library entry serves "
            f"({hier_topo[0]}x{hier_topo[1]}, {hier_nb} B) — run "
            "tools/accl_synth.py --export --tiers "
            f"{hier_topo[0]}x{hier_topo[1]}")
    cells = [
        dict(name="allreduce_hand", op=Operation.allreduce, nbytes=4096,
             tuning=tuning_hand, expect="hand"),
        dict(name="allreduce_synth", op=Operation.allreduce, nbytes=4096,
             tuning=tuning_synth, expect="synth",
             gate=("allreduce_hand", 1.3, "synth_allreduce_beats_hand")),
        dict(name="reduce_scatter_hand", op=Operation.reduce_scatter,
             nbytes=16384, tuning=tuning_hand, expect="hand"),
        dict(name="reduce_scatter_synth", op=Operation.reduce_scatter,
             nbytes=16384, tuning=tuning_synth, expect="synth",
             gate=("reduce_scatter_hand", 1.2,
                   "synth_reduce_scatter_beats_hand")),
        dict(name="allgather_hand", op=Operation.allgather, nbytes=16384,
             tuning=tuning_hand, expect="hand"),
        # refit=False: the hier pair sits OUTSIDE the alpha-beta wire
        # model's domain on this mesh (the flat twin is dominated by
        # per-segment re-dispatch, which the model deliberately does
        # not describe — that pathology is the hier cell's whole
        # point), so its samples must not enter the link refit
        dict(name="allreduce_flat_hier_twin", op=Operation.allreduce,
             nbytes=hier_nb, tuning=tuning_hand, expect="hand",
             rounds=6, warm=2, refit=False),
        # tiered_ok=False: the hand-written striped composition is now
        # the SLOW TWIN of the tiered synthesized cell below, so this
        # cell pins the composition through the twin-measurement
        # escape (select_algorithm tiered_synth_ok=False) the way
        # tuning_hand pins the hand cells — through the register path
        # the in-window arbitration would otherwise resolve away
        # rounds=24 on the two fast two-tier cells (a dispatch costs
        # ~4 ms here, unlike their 1.4 s/dispatch flat twin): their
        # gate ratio margin is ~1.15x, which a 6-round median
        # demonstrably flaked through on this CPU-share-throttled host
        dict(name="allreduce_hier", op=Operation.allreduce,
             nbytes=hier_nb, tuning=tuning_hier, expect="hier",
             topology=hier_topo, rounds=24, warm=2, refit=False,
             tiered_ok=False,
             gate=("allreduce_flat_hier_twin", 10.0,
                   "hier_allreduce_beats_flat")),
        # the pod-scale synthesis claim (ROADMAP item 3): inside the
        # SAME register window at the SAME payload, the in-window
        # arbitration must pick the committed tiered hop-DAG over the
        # striped composition by predicted time (the shaped-link
        # predicted margin — 1.68x under the shipped per-tier
        # calibration — is --hier-gate's leg). The MEASURED floor is
        # 0.6x, not 1.0x: on this functional CPU tier the tiered
        # program's extra log-step dispatch structure is bound by
        # per-dispatch XLA overhead the wire model deliberately does
        # not describe, and the re-run arbitration measured a stable
        # 0.63-0.73x band across library versions (see the
        # synth_tier_arbitration verdict in BASELINE_BENCH.json's
        # refit record) — the floor below that band still trips if
        # the compiled tiered program genuinely collapses
        dict(name="allreduce_synth_tier", op=Operation.allreduce,
             nbytes=hier_nb, tuning=tuning_hier, expect="synth_tier",
             topology=hier_topo, rounds=24, warm=2, refit=False,
             gate=("allreduce_hier", 0.6,
                   "synth_tier_matches_hier")),
    ]
    synth_cells = [(c["name"], c["op"], c["nbytes"], c["gate"][1])
                   for c in cells
                   if c["expect"] == "synth" and "gate" in c]
    rng = np.random.default_rng(1234)
    prepared = []
    for c in cells:
        name, op, nbytes = c["name"], c["op"], c["nbytes"]
        count = max(nbytes // 4, 1)
        sel_kw = dict(kw)
        if c.get("topology") is not None:
            sel_kw.update(topology=c["topology"], tier_links=tiers,
                          tiered_synth_ok=c.get("tiered_ok", True))
        plan = select_algorithm(op, count, 4, world, tuning=c["tuning"],
                                **sel_kw)
        want = {"synth": Algorithm.SYNTHESIZED,
                "synth_tier": Algorithm.SYNTHESIZED,
                "hier": Algorithm.HIER_RS_AR_AG}.get(c["expect"])
        if want is not None and plan.algorithm != want:
            raise SystemExit(
                f"FAIL: {name}/w{world}/{nbytes}: measured crossovers "
                f"did not select {want.name} (got {plan.algorithm.name})")
        if c["expect"] == "synth_tier":
            from accl_tpu.sequencer import synthesis as _sm

            spec = _sm.entry_for_key(plan.synth_key).spec
            if tuple(spec.tiers) != tuple(c["topology"]):
                raise SystemExit(
                    f"FAIL: {name}/w{world}/{nbytes}: arbitration "
                    f"selected {plan.synth_key}, not a "
                    f"{c['topology']} tiered entry")
        if want is None and plan.algorithm in (Algorithm.SYNTHESIZED,
                                               Algorithm.HIER_RS_AR_AG):
            raise SystemExit(
                f"FAIL: {name}/w{world}/{nbytes}: hand-written baseline "
                f"cell unexpectedly selected {plan.algorithm.name}")
        opts = CallOptions(scenario=op, count=count,
                           function=int(ReduceFunction.SUM),
                           data_type=DataType.float32)
        fn = comp.lower(opts, plan)
        in_elems = count * world if op == Operation.reduce_scatter \
            else count
        x = rng.integers(-50, 50, (world, in_elems)).astype(np.float32)
        for _ in range(c.get("warm", 5)):
            jax.block_until_ready(fn(x))
        sid = f"{name}/w{world}/{nbytes}"
        m, b = coefficients(op, plan, count, 4, world,
                            rx_buf_bytes=DEFAULT_EAGER_RX_BUF_SIZE)
        prepared.append((sid, fn, x, plan.algorithm.name, m, b,
                         c.get("rounds", 40), c.get("refit", True)))

    # the moe_dispatch cells (ROADMAP item 4): the fused+quantized MoE
    # layer step (ONE prepared-program dispatch, int8 wire via the
    # measured ALLTOALL_COMPRESS_MIN_COUNT register) vs the
    # descriptor-per-stage eager form at the same wire (the measured
    # fusion claim — the slow twin), plus the eager fp32 form as an
    # ungated trajectory section (on this memcpy-wire mesh the int8
    # byte win is invisible to wall clock by construction; its time
    # claim is the calibrated-link prediction --moe-gate gates).
    # refit=False: sequence dispatch + expert compute sit outside the
    # alpha-beta wire model's domain.
    moe_nb = 8 * 1024
    moe_tuned = _moe_harness(jax, world, moe_nb, tuned=True)
    moe_plain = _moe_harness(jax, world, moe_nb, tuned=False)
    moe_cells = [
        ("moe_dispatch_fused_int8", "MOE_FUSED_INT8_SEQ",
         lambda: moe_tuned["step"]("fused")),
        ("moe_dispatch_eager_int8", "MOE_EAGER3_INT8",
         lambda: moe_tuned["step"]("eager3")),
        ("moe_dispatch_eager_fp32", "MOE_EAGER3_FP32",
         lambda: moe_plain["step"]("eager3")),
    ]
    for name, label, mfn in moe_cells:
        for _ in range(3):
            mfn()
        prepared.append((f"{name}/w{world}/{moe_nb}", mfn, None, label,
                         0.0, 0.0, 40, False))

    # the train-step overlap cells (ROADMAP item 4): the fused
    # stripe-overlapped transformer train step (ONE dispatch, stripe
    # count from the COMMITTED compute_fit + shaped-link crossover —
    # the same calibration ACCL.autotune reads) vs the serial
    # dispatch->compute form a register-0 caller actually runs (three
    # eager dispatches whose allreduce is the rx-geometry segmented
    # ring — the hier twin's flat-segmented posture). Steady-state
    # convention (inputs resident, results left on device);
    # refit=False: model compute + sequence dispatch sit outside the
    # alpha-beta wire model's domain. The serial cell costs seconds
    # per dispatch BY DESIGN (that pathology is the overlap cell's
    # whole point), so its rounds are bounded like the hier twin's.
    from accl_tpu.models.transformer import train_param_count
    from accl_tpu.telemetry.feedback import default_compute_fit

    cfit = default_compute_fit()
    if cfit is None:
        raise SystemExit(
            "FAIL: timing model carries no compute_fit — run "
            "bench.py --overlap-gate to calibrate the train-step "
            "compute term")
    ocfg = _overlap_cfg(jax)
    ograd = train_param_count(ocfg) * 4
    olap_reg = int(tuning_crossovers(
        link, world=world, tier_links=tiers,
        compute_fit=cfit)["overlap_min_bytes"])
    if not 0 < olap_reg <= ograd:
        raise SystemExit(
            f"FAIL: train_step_overlap cell unavailable: the "
            f"calibrated overlap window ({olap_reg} B) does not cover "
            f"the {ograd} B gradient; re-run bench.py --overlap-gate "
            "(and --write-baseline if the window legitimately moved)")
    orng = np.random.default_rng(17)
    otok = orng.integers(0, ocfg.vocab, (world, 1, 8)).astype(np.int32)
    otgt = np.roll(otok, -1, axis=2)
    o_fused = _overlap_harness(jax, world, ocfg, otok, otgt,
                               serial=False, overlap_reg=olap_reg)
    o_serial = _overlap_harness(jax, world, ocfg, otok, otgt,
                                serial=True, overlap_reg=0)
    o_stripes = o_fused["prog"].plans[1].stripes
    if o_stripes <= 1:
        raise SystemExit(
            "FAIL: train_step_overlap cell selected a serial plan "
            f"(stripes={o_stripes}) inside the register window")
    train_cells = [
        ("train_step_overlap", f"TRAIN_OVERLAP_RS_AG_S{o_stripes}",
         o_fused["step"], 6, 2),
        ("train_step_serial", "TRAIN_SERIAL_SEGMENTED",
         o_serial["step"], 3, 1),
    ]
    for name, label, tfn, rounds_, warm_ in train_cells:
        for _ in range(warm_):
            jax.block_until_ready(tfn())
        prepared.append((f"{name}/w{world}/{ograd}", tfn, None, label,
                         0.0, 0.0, rounds_, False))

    # the decode-step cells (ISSUE 18): the serving latency floor as a
    # tracked trajectory pair — the fused one-dispatch KV-decode step
    # vs the dispatch-per-layer eager twin at the same model/plans.
    # refit=False: consumer compute + sequence dispatch sit outside
    # the alpha-beta wire model's domain; the eager twin pays
    # 7*n_layers+1 facade dispatches per step BY DESIGN (that seam tax
    # is the fused cell's whole point), so its rounds are bounded
    dec = _decode_harness(jax, world)
    dec_nb = dec["nbytes"]
    decode_cells = [
        ("decode_step_fused", "DECODE_FUSED_SEQ",
         lambda: dec["step"]("fused"), 24, 2),
        ("decode_step_eager", "DECODE_EAGER_LAYERS",
         lambda: dec["step"]("eager"), 6, 1),
    ]
    for name, label, dfn, rounds_, warm_ in decode_cells:
        for _ in range(warm_):
            dfn()
        prepared.append((f"{name}/w{world}/{dec_nb}", dfn, None, label,
                         0.0, 0.0, rounds_, False))

    samples = {sid: [] for sid, *_ in prepared}
    for r in range(max(p[6] for p in prepared)):
        for sid, fn, x, _label, _m, _b, rounds, _refit in prepared:
            if r >= rounds:
                continue
            t0 = time.perf_counter()
            jax.block_until_ready(fn() if x is None else fn(x))
            samples[sid].append(time.perf_counter() - t0)
    rows = {}
    for sid, _fn, _x, label, m, b, _rounds, refit_ok in prepared:
        sec = float(np.median(samples[sid]))
        rows[sid] = {"seconds": sec, "messages": m, "bytes": b,
                     "algorithm": label,
                     "refit": refit_ok}
        print(f"  {sid:36s} {sec * 1e6:10.1f} us  "
              f"{label}", file=sys.stderr)
    by_name = {c["name"]: c for c in cells}
    gates = [
        {"name": f"{c['gate'][2]}_w{world}_{c['nbytes']}B",
         "fast": f"{c['name']}/w{world}/{c['nbytes']}",
         "slow": (f"{c['gate'][0]}/w{world}/"
                  f"{by_name[c['gate'][0]]['nbytes']}"),
         "min_ratio": c["gate"][1]}
        for c in cells if "gate" in c
    ]
    gates.append({
        "name": f"moe_dispatch_fused_beats_eager_w{world}_{moe_nb}B",
        "fast": f"moe_dispatch_fused_int8/w{world}/{moe_nb}",
        "slow": f"moe_dispatch_eager_int8/w{world}/{moe_nb}",
        "min_ratio": 1.0})
    gates.append({
        "name": f"train_step_overlap_beats_serial_w{world}_{ograd}B",
        "fast": f"train_step_overlap/w{world}/{ograd}",
        "slow": f"train_step_serial/w{world}/{ograd}",
        "min_ratio": 10.0})
    # measured ~27x on this mesh (bench --serve-gate); 3x floor leaves
    # room for host variance while still catching a collapsed fusion
    gates.append({
        "name": f"decode_step_fused_beats_eager_w{world}_{dec_nb}B",
        "fast": f"decode_step_fused/w{world}/{dec_nb}",
        "slow": f"decode_step_eager/w{world}/{dec_nb}",
        "min_ratio": 3.0})
    return rows, world, synth_cells, gates


def _check_main():
    """bench.py --check: diff measured section times against the
    committed BASELINE_BENCH.json tolerance bands, enforce the
    synthesized-schedule speedup gates, and require the LinkParams
    refit from this run's samples to AGREE that the synthesized
    schedules win their measured cells (a flipped verdict means
    prediction and measurement diverged and the crossover registers are
    stale) — the perf trajectory as an exit code, not prose (ROADMAP
    item 5). Refit-vs-shipped median residuals are reported in the JSON
    artifact but not gated: five cells on a noisy CPU mesh are a
    verdict check, not a calibration set (bench --trace owns the
    residual-improvement gate). `--write-baseline` regenerates the
    table from this run instead."""
    from accl_tpu.sequencer.timing import calibrate

    write = "--write-baseline" in sys.argv
    rows, world, synth_cells, gates = _check_sections(__import__("jax"))

    # metrics section: run every measured cell through the SAME span ->
    # metrics rule the live observer applies (one native-shaped event
    # per cell, prediction under the shipped link), so --check also
    # proves the registry + sentinel machinery digests the real cell
    # population — a wiring regression (lost labels, broken exposition,
    # sentinel crash) fails here before it fails in production
    from accl_tpu.telemetry.metrics import (
        DriftSentinel,
        MetricsObserver,
        MetricsRegistry,
    )

    shipped_for_obs = _shipped_link()
    obs = MetricsObserver(MetricsRegistry(), DriftSentinel())
    for sid, r in sorted(rows.items()):
        obs({"name": sid.split("/")[0], "cat": "native", "track": "check",
             "ts_ns": 0, "dur_ns": int(r["seconds"] * 1e9),
             "args": {"op": sid.split("/")[0], "world": world,
                      "algorithm": r["algorithm"],
                      "measured_s": r["seconds"],
                      "coef_messages": r["messages"],
                      "coef_bytes": r["bytes"],
                      "predicted_s": shipped_for_obs.seconds(
                          r["messages"], r["bytes"])}})
    obs_calls = sum(row["value"] for row in obs.registry.snapshot()
                    ["counters"].get("accl_calls_total", []))
    obs_expo_lines = len(obs.registry.expose_text().splitlines())

    # refit-vs-shipped: fit alpha/beta to this run's (m, b, t) samples
    # and compare median relative residuals against the shipped link
    samples = [(r["messages"], r["bytes"], r["seconds"])
               for r in rows.values() if r.get("refit", True)]
    refit = calibrate(samples)
    shipped = _shipped_link()

    def med_residual(link):
        res = [abs(link.seconds(m, b) - t) / t for m, b, t in samples]
        return float(np.median(res))

    r_refit, r_shipped = med_residual(refit), med_residual(shipped)
    print(f"  link refit alpha {refit.alpha * 1e6:.1f} us beta "
          f"{refit.beta / 1e9:.3f} GB/s: median residual "
          f"{r_refit:.2f} vs shipped {r_shipped:.2f}", file=sys.stderr)

    # refit-vs-shipped agreement on the question the registers answer:
    # under THIS host's own calibration, the synthesized schedules must
    # still predict as the winners of their measured cells — if the
    # refit link flips the verdict, prediction and measurement have
    # diverged and the crossover registers are stale
    from accl_tpu.sequencer import synthesis as _synth

    refit_disagreements = []
    for name, op, nbytes, _ratio in synth_cells:
        # derived from the one cells table, so every measured synth
        # cell IS checked — a retuned cell can't silently orphan its
        # refit-agreement check
        key_sec = f"{name}/w{world}/{nbytes}"
        count = max(nbytes // 4, 1)
        key = _synth.select_entry(op, world, nbytes)
        if key is None:
            refit_disagreements.append(
                f"{key_sec}: no library entry serves the cell")
            continue
        spec = _synth.entry_for_key(key).spec
        t_s = _synth.predict_spec(refit, spec, count, 4)
        t_h = _synth.hand_written_best(refit, op, count, 4, world)
        if t_s >= t_h:
            refit_disagreements.append(
                f"{key_sec}: refit link predicts synthesized "
                f"{t_s * 1e6:.0f} us >= hand-written {t_h * 1e6:.0f} us "
                "— predicted and measured winners disagree")

    if write:
        doc = {
            "schema": 1,
            "host": f"virtual {world}-device CPU mesh (functional CI "
                    "tier; seconds are NOT hardware numbers)",
            "tol_rel": 5.0,
            "sections": {sid: {"seconds": r["seconds"],
                               "algorithm": r["algorithm"]}
                         for sid, r in rows.items()},
            "gates": gates,
            "refit": {"alpha_us": refit.alpha * 1e6,
                      "beta_gbps": refit.beta / 1e9,
                      "median_residual": r_refit},
            # the observability contract (bench --obs-gate + the
            # metrics section above): committed so a config retune is
            # a reviewed baseline diff, not a silent drift
            "observability": {
                "overhead_budget_pct": OBS_OVERHEAD_BUDGET * 100,
                "sentinel_window": OBS_SENTINEL_WINDOW,
                "sentinel_min_samples": OBS_SENTINEL_MIN_SAMPLES,
                "sentinel_band_floor": OBS_SENTINEL_BAND_FLOOR,
                "spans_per_call": OBS_SPANS_PER_CALL,
            },
            # the multi-tenant gate contract (bench --tenant-gate):
            # the committed band the small-tenant p99 is judged
            # against plus the soak shape — committed so a band
            # retune is a reviewed baseline diff, not a silent drift
            "tenant": {
                "small_tenants": TENANT_GATE_SMALL_TENANTS,
                "bulk_wire_bytes": TENANT_GATE_BULK_WIRE_BYTES,
                "bulk_chunk_elems": TENANT_GATE_BULK_CHUNK_ELEMS,
                "p99_band": TENANT_GATE_P99_BAND,
                "hol_chunks": TENANT_GATE_HOL_CHUNKS,
                "fair_share_tol": TENANT_GATE_FAIR_SHARE_TOL,
            },
        }
        # arbitration verdicts in the refit record are reviewed human
        # decisions (e.g. the synth_tier measured-floor adjustment),
        # not measurements — carry them forward from the committed
        # baseline so a re-baseline can't silently drop them
        if BASELINE_BENCH.exists():
            old_refit = json.loads(BASELINE_BENCH.read_text()) \
                .get("refit", {})
            for k, v in old_refit.items():
                if k.endswith("_arbitration"):
                    doc["refit"][k] = v
        BASELINE_BENCH.write_text(json.dumps(doc, indent=1,
                                             sort_keys=True) + "\n")
        print(f"wrote {BASELINE_BENCH}", file=sys.stderr)

    base = json.loads(BASELINE_BENCH.read_text())
    tol = float(base.get("tol_rel", 4.0))
    failures = []
    for sid, entry in base["sections"].items():
        got = rows.get(sid)
        if got is None:
            failures.append(f"section {sid} in baseline but not "
                            "measured (bench drift)")
            continue
        if got["algorithm"] != entry.get("algorithm",
                                         got["algorithm"]):
            failures.append(
                f"{sid}: algorithm changed "
                f"{entry['algorithm']} -> {got['algorithm']} "
                "(selection regression; re-baseline deliberately)")
        if got["seconds"] > entry["seconds"] * tol:
            failures.append(
                f"{sid}: measured {got['seconds'] * 1e6:.1f} us > "
                f"baseline {entry['seconds'] * 1e6:.1f} us x{tol:g} "
                "tolerance band")
    for gate in base.get("gates", []):
        fast = rows.get(gate["fast"])
        slow = rows.get(gate["slow"])
        if fast is None or slow is None:
            failures.append(f"gate {gate['name']}: missing section")
            continue
        ratio = slow["seconds"] / fast["seconds"]
        verdict = "ok" if ratio >= gate["min_ratio"] else "FAIL"
        print(f"  gate {gate['name']}: {ratio:.2f}x "
              f"(need >= {gate['min_ratio']:g}x) {verdict}",
              file=sys.stderr)
        if ratio < gate["min_ratio"]:
            failures.append(
                f"gate {gate['name']}: measured speedup {ratio:.2f}x "
                f"below the {gate['min_ratio']:g}x bar — the "
                "synthesized-schedule claim no longer holds")
    failures.extend(refit_disagreements)
    # metrics-section integrity: every measured cell must have landed in
    # the registry, and the committed observability config must match
    # this build's constants (a retuned budget/window ships via
    # --write-baseline, never silently)
    if obs_calls != len(rows):
        failures.append(
            f"metrics registry digested {obs_calls:g} of {len(rows)} "
            "measured cells — the span->metrics rule dropped cells")
    committed_obs = base.get("observability")
    build_obs = {
        "overhead_budget_pct": OBS_OVERHEAD_BUDGET * 100,
        "sentinel_window": OBS_SENTINEL_WINDOW,
        "sentinel_min_samples": OBS_SENTINEL_MIN_SAMPLES,
        "sentinel_band_floor": OBS_SENTINEL_BAND_FLOOR,
        "spans_per_call": OBS_SPANS_PER_CALL,
    }
    if committed_obs != build_obs:
        failures.append(
            f"observability config drift: committed {committed_obs} vs "
            f"build {build_obs} (re-run --write-baseline deliberately)")
    committed_ten = base.get("tenant")
    build_ten = {
        "small_tenants": TENANT_GATE_SMALL_TENANTS,
        "bulk_wire_bytes": TENANT_GATE_BULK_WIRE_BYTES,
        "bulk_chunk_elems": TENANT_GATE_BULK_CHUNK_ELEMS,
        "p99_band": TENANT_GATE_P99_BAND,
        "hol_chunks": TENANT_GATE_HOL_CHUNKS,
        "fair_share_tol": TENANT_GATE_FAIR_SHARE_TOL,
    }
    if committed_ten != build_ten:
        failures.append(
            f"tenant-gate config drift: committed {committed_ten} vs "
            f"build {build_ten} (re-run --write-baseline deliberately)")
    print(json.dumps({
        "metric": "bench --check: measured-vs-baseline regression gate "
                  f"(w{world} CPU mesh, {len(rows)} sections, "
                  f"{len(base.get('gates', []))} speedup gates)",
        "value": len(failures),
        "unit": "regressions",
        "platform": "cpu-fallback",
        "refit_median_residual": round(r_refit, 3),
        "shipped_median_residual": round(r_shipped, 3),
        "metrics": {
            "cells_observed": obs_calls,
            "exposition_lines": obs_expo_lines,
            "sentinel": obs.sentinel.report(),
        },
    }))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        sys.exit(1)


def _flagship_setup(jax):
    """One flagship model configuration shared by the train and decode
    lanes (so both benchmark the SAME model): returns
    (cfg, batch, seq_or_ctx, mesh, params, peak_flops)."""
    from accl_tpu.models import TransformerConfig, init_params
    from accl_tpu.models.transformer import shard_params
    from accl_tpu.parallel import make_mesh

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_kv_heads=4, n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        batch, seq = 8, 1024
        # bf16 MXU peak per chip, by generation (unknown kinds report no
        # MFU rather than one computed against the wrong ceiling)
        kind = jax.devices()[0].device_kind.lower()
        if "v5 lite" in kind or "v5e" in kind:
            peak_flops = 197e12
        elif "v5p" in kind or "v5" in kind:
            peak_flops = 459e12
        else:
            peak_flops = None
    else:
        cfg = TransformerConfig(dtype="float32")
        batch, seq = 4, 64
        peak_flops = None

    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1},
                     devices=jax.devices()[:1])
    params = shard_params(init_params(cfg, jax.random.key(0)), cfg, mesh)
    return cfg, batch, seq, mesh, params, peak_flops


def bench_flagship(jax):
    """Flagship training-step lane: tokens/s and approximate model-FLOPs
    utilization of the compiled dense-transformer train step (forward +
    backward + grad sync + SGD) on the attached device. The reference has
    no model layer — this lane shows the framework's compute path is
    MXU-shaped (bf16 matmuls), complementing the collective lanes.
    Writes accl_log/flagship.csv."""
    from accl_tpu.models import make_train_step
    from accl_tpu.models.transformer import demo_batch

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    cfg, batch, seq, mesh, params, peak_flops = _flagship_setup(jax)
    tokens, targets = demo_batch(cfg, mesh, batch=batch, seq=seq)
    step = make_train_step(cfg, mesh, lr=1e-3)

    def make_fn(k):
        def rep(p, t, g):
            loss = None
            for _ in range(k):
                p, loss = step(p, t, g)  # param chain serializes steps
            return loss
        return rep

    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree.leaves(params))
    T = batch * seq
    # standard fwd+bwd estimate: 6 FLOPs/param/token + attention term
    flops_step = 6.0 * n_params * T + 12.0 * cfg.n_layers * T * seq * cfg.d_model
    est = flops_step / (peak_flops or 50e9) + 1e-3
    sec, k, snr, _resolved = _timeit_loop(make_fn, (params, tokens, targets),
                                          est, target=1.0, kmax=50, jax=jax)
    tok_s = T / sec
    mfu = flops_step / sec / peak_flops * 100 if peak_flops else float("nan")
    print(f"  flagship_train_step  {n_params/1e6:.0f}M params  "
          f"{sec*1e3:8.2f} ms/step  {tok_s:9.0f} tok/s  MFU {mfu:5.1f}%  "
          f"(K={k})", file=sys.stderr)
    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    name = "flagship_cpu.csv" if not on_tpu else "flagship.csv"
    with open(outdir / name, "w") as f:
        f.write("NParams,TokensPerStep,SecPerStep,TokensPerSec,"
                "ApproxFLOPsPerStep,MFUpct,SNR\n")
        f.write(f"{n_params},{T},{sec:.6e},{tok_s:.1f},"
                f"{flops_step:.3e},{mfu:.2f},{snr:.1f}\n")


def bench_decode(jax):
    """Inference lane: incremental KV-cache decode throughput (tokens/s
    and per-token latency) of the compiled single-position step on the
    attached device — the serving-path complement of the train-step lane.
    Writes accl_log/decode.csv."""
    import jax.numpy as jnp

    from accl_tpu.models import init_kv_cache, make_decode_step

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    cfg, batch, ctx, mesh, params, _peak = _flagship_setup(jax)
    step = make_decode_step(cfg, mesh)
    cache = init_kv_cache(cfg, mesh, batch, max_len=ctx)
    tok = jnp.zeros((batch, 1), jnp.int32)

    # warm the cache to mid-context so the attention reads a realistic
    # window, then time steps at a FIXED position (chained cache, one
    # dispatch per generated token — the serving shape). The step donates
    # its cache (in-place KV update), so the live cache threads through a
    # closure across timing invocations rather than riding args.
    pos = jnp.array([ctx // 2], jnp.int32)
    logits, cache = step(params, cache, tok, pos)
    state = {"cache": cache}

    def make_fn(k):
        def rep(p, t):
            c = state["cache"]
            lg = None
            for i in range(k):
                lg, c = step(p, c, t, pos)
            state["cache"] = c
            return lg
        return rep

    sec, k, snr, resolved = _timeit_loop(make_fn, (params, tok),
                                         1e-3, target=1.0, kmax=400, jax=jax)
    tok_s = batch / sec
    regime = "ok" if resolved else "noise"
    print(f"  decode_step  batch={batch} ctx={ctx}  {sec*1e3:8.3f} ms/tok-step"
          f"  {tok_s:9.0f} tok/s  (K={k}, {regime})", file=sys.stderr)
    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    name = "decode_cpu.csv" if not on_tpu else "decode.csv"
    with open(outdir / name, "w") as f:
        f.write("Batch,Context,SecPerStep,TokensPerSec,SNR,Regime\n")
        f.write(f"{batch},{ctx},{sec:.6e},{tok_s:.1f},{snr:.1f},{regime}\n")


_PROBE_CACHE = pathlib.Path(__file__).parent / "accl_log" / \
    "tpu_probe_cache.json"


def _tpu_reachable_backoff(attempts=(20, 40, 90), cache_ttl_s=900.0) -> bool:
    """Bounded-backoff TPU probe with a run-scoped verdict cache.

    A live tunnel answers `jax.devices()` in seconds, so the probe
    starts with a short rope and only escalates toward the full
    watchdog budget when earlier attempts time out (a wedged tunnel
    never answers — BENCH_r05 paid the whole 'device probe hung past
    150s' before falling back). The verdict lands in
    accl_log/tpu_probe_cache.json with a timestamp, so every later
    bench invocation of the same run (the probe-loop payload runs the
    suite, the full sweep, and the timing-model refresh back to back)
    reads the cached verdict instead of re-paying a multi-minute hang;
    a cache older than cache_ttl_s re-probes, since tunnels do recover
    (tools/tpu_probe_loop.py exists to catch exactly that).

    The verdict is keyed by the JAX_PLATFORMS environment too, not TTL
    alone: a forced-CPU invocation (JAX_PLATFORMS=cpu) probes and
    caches ok=False by construction, and without the key a real-TPU
    run inside the TTL would read that poisoned verdict and silently
    fall back — every artifact's `platform` field would claim
    cpu-fallback on a healthy chip. A cache written under a different
    JAX_PLATFORMS is ignored and re-probed."""
    plat_env = os.environ.get("JAX_PLATFORMS", "")
    try:
        c = json.loads(_PROBE_CACHE.read_text())
        if (time.time() - float(c["ts"]) < cache_ttl_s
                and c.get("jax_platforms", "") == plat_env):
            print(f"TPU probe: cached verdict ok={c['ok']} "
                  f"({time.time() - c['ts']:.0f}s old)", file=sys.stderr)
            return bool(c["ok"])
    except (OSError, ValueError, KeyError):
        pass
    from __graft_entry__ import _probe_tpu  # the one shared watchdog

    ok = False
    for i, t in enumerate(attempts):
        ok, detail = _probe_tpu(timeout_s=t)
        if ok:
            break
        print(f"TPU probe attempt {i + 1}/{len(attempts)} "
              f"(timeout {t}s): {detail.splitlines()[0]}", file=sys.stderr)
    _PROBE_CACHE.parent.mkdir(exist_ok=True)
    try:
        _PROBE_CACHE.write_text(json.dumps(
            {"ok": ok, "ts": time.time(), "jax_platforms": plat_env}))
    except OSError:
        pass  # probe verdict is still good for this process
    return ok


def main():
    if os.environ.get("ACCL_BENCH_NO_FALLBACK") != "1":
        # shared subprocess watchdog (see __graft_entry__._probe_tpu): a
        # wedged tunnel hangs jax.devices() forever, and probing in a
        # subprocess keeps THIS process's backend un-touched
        if not _tpu_reachable_backoff():
            # TPU wedged: re-exec on the CPU backend so the driver still
            # gets a (clearly labeled) result instead of a hang
            import subprocess

            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["ACCL_BENCH_NO_FALLBACK"] = "1"
            env["ACCL_BENCH_CPU_FALLBACK"] = "1"
            # replace PYTHONPATH: repo only, no TPU sitecustomize dir
            env["PYTHONPATH"] = str(pathlib.Path(__file__).parent)
            print("TPU unreachable within watchdog; CPU fallback",
                  file=sys.stderr)
            r = subprocess.run([sys.executable, __file__], env=env)
            sys.exit(r.returncode)

    import jax

    sizes = [1 << k for k in range(10, 31, 4)]  # 1 KB .. 1 GB, x16 steps
    print(f"devices: {jax.devices()}", file=sys.stderr)
    rows = bench_combine(jax, sizes)

    world = len(jax.devices())
    # the compiled allreduce program is timed at EVERY world size: with
    # one real chip it measures dispatch + datapath of the degenerate
    # schedule (the BASELINE.md sweep's on-chip component); with a CPU
    # mesh it also exercises the wire path
    ar_sizes = [1 << k for k in range(12, 27, 6)]
    rows += bench_collective(jax, "allreduce", ar_sizes, min(world, 8))

    # fused call-sequence lane (one dispatch vs three) + the pallas ring
    # segment-overlap A/B (TPU only; self-gated)
    try:
        seq_rows, _ = bench_sequence(jax, min(world, 8))
        rows += seq_rows
    except Exception as e:
        print(f"sequence lane failed: {e!r}", file=sys.stderr)
    try:
        rows += bench_ring_overlap(jax, min(world, 8))
    except Exception as e:
        print(f"ring-overlap lane failed: {e!r}", file=sys.stderr)

    # ACCL_BENCH_FULL=1: the reference's 8-collective sweep shape
    # (bench.cpp:25-61) — every collective through its compiled schedule.
    # Off by default because each (op, size) pair costs a remote compile
    # on the tunneled chip; the probe-loop payload runs it.
    if os.environ.get("ACCL_BENCH_FULL") == "1":
        full_sizes = [1 << k for k in range(12, 25, 6)]
        # on the real chip, extend every w1 lane into the regime where
        # datapath time (bytes / HBM rate) clearly exceeds the ~0.5 ms
        # relay dispatch cost, so the timing model's TPU tier can resolve
        # a finite datapath beta instead of clamping it to inf
        # (reference: device-side cycle counter separates call overhead
        # from wire time, xrtdevice.cpp:242-249)
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
        if on_tpu:
            full_sizes = full_sizes + [1 << 28]
        for op_name in ("bcast", "scatter", "gather", "allgather",
                        "reduce", "reduce_scatter", "alltoall"):
            rows += bench_collective(jax, op_name, full_sizes,
                                     min(world, 8))
        rows += bench_collective(jax, "allreduce", [1 << 28],
                                 min(world, 8))
        try:
            bench_flagship(jax)
        except Exception as e:  # the sweep rows must survive a flagship
            print(f"flagship lane failed: {e!r}", file=sys.stderr)
        try:
            bench_decode(jax)
        except Exception as e:
            print(f"decode lane failed: {e!r}", file=sys.stderr)

    outdir = pathlib.Path(__file__).parent / "accl_log"
    outdir.mkdir(exist_ok=True)
    # CPU runs (fallback or direct) write to their own CSV so they can
    # never clobber the committed TPU-measured artifact PARITY.md cites
    is_cpu = (os.environ.get("ACCL_BENCH_CPU_FALLBACK") == "1"
              or jax.default_backend() == "cpu")
    csv_name = "profile_cpu.csv" if is_cpu else "profile.csv"
    # Regime column: only rows whose working set clearly exceeds VMEM
    # measure HBM throughput ("stream"); smaller points measure dispatch
    # latency / on-chip residency ("latency") and their GBps must not be
    # read as bandwidth; rows whose device time never resolved above the
    # relay jitter even at kmax are "noise" — their Seconds is the jitter
    # resolution floor (an upper bound on the true time; GBps a lower
    # bound), not a measurement.
    with open(outdir / csv_name, "w") as f:
        f.write("Test,Bytes,Seconds,GBps,Regime\n")
        for t, b, s, g, snr, resolved in rows:
            regime = ("noise" if not resolved
                      else "stream" if b >= 256 * 1024 * 1024
                      else "latency")
            f.write(f"{t},{b},{s:.6e},{g:.3f},{regime}\n")

    # Headline: the fully HBM-streaming regime (>= 256 MB: a+b working set
    # well past VMEM, so every loop iteration pays full memory traffic) —
    # the apples-to-apples counterpart of the reference's line-rate-bound
    # data plane. Smaller sizes in the CSV run partially VMEM-resident and
    # measure lane latency / on-chip throughput instead.
    combine_rows = [r for r in rows
                    if r[0] == "combine_sum_fp32"
                    and r[1] >= 256 * 1024 * 1024 and r[5]]
    unresolved_headline = not combine_rows
    if unresolved_headline:  # nothing resolved: publish the floor, labeled
        combine_rows = [r for r in rows if r[0] == "combine_sum_fp32"
                        and r[1] >= 256 * 1024 * 1024]
    p50 = float(np.median([r[3] for r in combine_rows]))
    on_tpu_run = any(r[0].endswith("_pallas") for r in rows)
    note = ""
    if os.environ.get("ACCL_BENCH_CPU_FALLBACK") == "1":
        note = " [CPU FALLBACK: TPU unreachable"
        # point the one-line record at the last committed on-chip number
        # so a wedged tunnel doesn't read as a perf regression (the value
        # itself stays the honest CPU measurement)
        try:
            for line in (outdir / "profile.csv").read_text().splitlines():
                parts = line.split(",")
                if parts[0] == "combine_sum_fp32" and parts[-1] == "stream":
                    note += (f"; committed TPU artifact: {float(parts[3]):.1f}"
                             " GB/s at this point, accl_log/profile.csv")
                    break
        except (OSError, ValueError, IndexError):
            pass
        note += "]"
    if unresolved_headline:
        # the value derives from the jitter-resolution floor: a LOWER
        # bound on throughput, not a measurement — say so in the one
        # line the driver records
        note += (" [UNRESOLVED: at relay jitter floor; value is a lower"
                 " bound, not a measurement]")
    result = {
        "metric": "reduce_ops combine lane HBM-streaming throughput, "
                  "1GB fp32 (full 1KB-1GB sweep"
                  + (" + pallas variant" if on_tpu_run else "")
                  + " in CSV)" + note,
        "value": round(p50, 2),
        "unit": "GB/s",
        # the TPU-vs-CPU-fallback distinction as SCHEMA, not prose:
        # "tpu" means the value is an on-chip measurement comparable to
        # the pinned 298 GB/s artifact; "cpu-fallback" means the TPU
        # was unreachable and the value is functional-regime noise that
        # must never be read as a perf trajectory (ROADMAP item 5;
        # tools/report_bench.py labels rounds by this field)
        "platform": "cpu-fallback" if is_cpu else "tpu",
        "vs_baseline": round(p50 / BASELINE_GBPS, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        _smoke_main()
    elif "--quant-gate" in sys.argv:
        _quant_gate_main()
    elif "--moe-gate" in sys.argv:
        _moe_gate_main()
    elif "--overlap-gate" in sys.argv:
        _overlap_gate_main()
    elif "--trace" in sys.argv:
        _trace_main()
    elif "--obs-gate" in sys.argv:
        _obs_gate_main()
    elif "--fault-gate" in sys.argv:
        _fault_gate_main()
    elif "--chaos-gate" in sys.argv:
        _chaos_gate_main()
    elif "--wire-gate" in sys.argv:
        _wire_gate_main()
    elif "--serve-gate" in sys.argv:
        _serve_gate_main()
    elif "--tenant-gate" in sys.argv:
        _tenant_gate_main()
    elif "--hier-gate" in sys.argv:
        _hier_gate_main()
    elif "--check" in sys.argv or "--write-baseline" in sys.argv:
        _check_main()
    else:
        main()
