#!/usr/bin/env python3
"""Runnable demo: train a model with its parallelism routed entirely
through accl-tpu schedules, with checkpoint/resume.

Two model families: the dense dp x sp x tp transformer (default) and the
expert-parallel MoE (--model moe, dp x ep with dispatch/combine through
the framework alltoall). Checkpointing is a TPU-first extension past the
reference (which, as a collectives library, has none — SURVEY.md §5):
parameters save/restore via orbax so an interrupted run resumes exactly.

Usage:
  python examples/train_lm.py --steps 20 --ckpt /tmp/accl_ckpt
  python examples/train_lm.py --steps 20 --ckpt /tmp/accl_ckpt  # resumes
  python examples/train_lm.py --model moe --steps 20
"""

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt", default=None, help="checkpoint directory")
    ap.add_argument("--cpu-devices", type=int, default=8)
    ap.add_argument("--model", choices=("dense", "moe"), default="dense")
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline stages for the dense model (layers "
                         "shard over a pp mesh axis, GPipe microbatching)")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize each block in the backward pass "
                         "(jax.checkpoint): O(1) activation memory")
    ap.add_argument("--top-k", type=int, default=1,
                    help="experts per token for --model moe")
    args = ap.parse_args()
    # model-specific flags fail loudly on the wrong path instead of
    # silently measuring the plain step
    if args.model == "moe" and (args.pp > 1 or args.remat):
        raise SystemExit("--pp/--remat apply to --model dense only")
    if args.model == "dense" and args.top_k != 1:
        raise SystemExit("--top-k applies to --model moe only")

    # a wedged TPU tunnel hangs jax.devices() forever — probe it in a
    # subprocess (the shared watchdog) and force CPU when unreachable
    from __graft_entry__ import _force_cpu, _tpu_reachable

    import jax

    if not _tpu_reachable(timeout_s=150):
        _force_cpu(args.cpu_devices)
    else:
        # device count locks at backend init; only affects the cpu
        # backend, harmless under a real TPU
        try:
            jax.config.update("jax_num_cpu_devices", args.cpu_devices)
        except Exception:
            pass

    import numpy as np

    from accl_tpu.parallel import factorize_devices, make_mesh

    n_dev = len(jax.devices())
    if args.model == "moe":
        from accl_tpu.models import (MoEConfig, init_moe_params,
                                     make_moe_train_step)
        from accl_tpu.models.moe import place_moe_params

        ep = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
        dp = n_dev // ep
        axes = {"dp": dp, "ep": ep}
        mesh = make_mesh(axes)
        cfg = MoEConfig(d_model=64, d_ff=128, n_experts=ep,
                        experts_per_rank=1, vocab=128, seq=32,
                        top_k=args.top_k)
        print(f"mesh {axes}; MoE with {cfg.n_experts} experts, "
              f"top-{cfg.top_k} routing")
        params = init_moe_params(cfg, jax.random.key(0))

        def place(p):
            return place_moe_params(p, cfg, mesh)

        def make_batch():
            rng = np.random.default_rng(0)
            b = 2 * n_dev
            tokens = rng.integers(0, cfg.vocab, (b, cfg.seq)).astype(np.int32)
            return tokens, np.roll(tokens, -1, 1)

        step = make_moe_train_step(cfg, mesh, lr=3e-2)
    else:
        from accl_tpu.models import (TransformerConfig, init_params,
                                     make_train_step)
        from accl_tpu.models.transformer import demo_batch, shard_params

        pp = max(1, args.pp)
        if pp > 1:
            if n_dev % pp:
                raise SystemExit(f"--pp {pp} does not divide {n_dev} devices")
            rest = n_dev // pp
            tp = 2 if rest % 2 == 0 else 1
            axes = {"dp": rest // tp, "sp": 1, "tp": tp, "pp": pp}
        else:
            axes = factorize_devices(n_dev)
        mesh = make_mesh(axes)
        heads = max(4, axes["tp"] * 2)
        # grouped-query shape when it divides cleanly: half the kv heads,
        # still a multiple of tp (kv heads shard over tp too)
        kv = heads // 2 if (heads // 2) % axes["tp"] == 0 else heads
        cfg = TransformerConfig(vocab=128, d_model=heads * 8, n_heads=heads,
                                n_kv_heads=kv, n_layers=max(2, pp),
                                d_ff=heads * 16)
        print(f"mesh {axes}; model d={cfg.d_model} heads={cfg.n_heads} "
              f"kv={cfg.kv_heads} layers={cfg.n_layers}"
              + (" remat" if args.remat else ""))
        params = init_params(cfg, jax.random.key(0))

        def place(p):
            return shard_params(p, cfg, mesh)

        def make_batch():
            # B_local = batch/dp must divide by the pp microbatch count
            batch = max(2, axes["dp"]) * max(pp, 2)
            return demo_batch(cfg, mesh, batch=batch,
                              seq=max(32, axes["sp"] * 16))

        step = make_train_step(cfg, mesh, lr=3e-2, remat=args.remat)

    start_step = 0

    ckptr = None
    if args.ckpt:
        import orbax.checkpoint as ocp

        path = pathlib.Path(args.ckpt).absolute()
        ckptr = ocp.StandardCheckpointer()
        latest = sorted(
            d for d in path.glob("step_*")
            if d.name.split("_")[1].isdigit()  # skip orbax tmp dirs from
        ) if path.exists() else []             # interrupted saves
        if latest:
            start_step = int(latest[-1].name.split("_")[1])
            params = ckptr.restore(latest[-1], params)
            print(f"resumed from {latest[-1]}")

    params = place(params)
    tokens, targets = make_batch()

    for s in range(start_step, start_step + args.steps):
        params, loss = step(params, tokens, targets)
        if s % 5 == 0 or s == start_step + args.steps - 1:
            print(f"step {s:4d}  loss {float(loss):.4f}")

    if ckptr is not None:
        target = pathlib.Path(args.ckpt).absolute() / \
            f"step_{start_step + args.steps:06d}"
        host_params = jax.tree.map(lambda x: np.asarray(x), params)
        if args.model == "dense" and args.pp > 1:
            # checkpoints stay in the mesh-independent per-layer list form,
            # so a run can resume onto a different pp width WHEN the model
            # depth matches (n_layers here is max(2, pp): pp<=2 widths
            # interchange; deeper pipelines need the same --pp to resume)
            from accl_tpu.models.transformer import unstack_layer_params

            host_params = unstack_layer_params(host_params, cfg.n_layers)
        ckptr.save(target, host_params, force=True)
        ckptr.wait_until_finished()
        print(f"saved {target}")


if __name__ == "__main__":
    main()
