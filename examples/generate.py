#!/usr/bin/env python3
"""Runnable demo: incremental (KV-cache) generation on a dp x tp mesh —
the inference half of the model family. Every tensor-parallel partial
sum in the decode step reduces through the framework's own ring
schedule, exactly as in training; the compiled step is position-generic
(static shapes), so one program serves the whole generation.

Usage:
  python examples/generate.py --steps 16            # greedy
  python examples/generate.py --steps 16 --temp 0.8 # sampled
"""

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=16,
                    help="tokens to generate after the prompt")
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--temp", type=float, default=0.0,
                    help="0 = greedy, else softmax temperature")
    ap.add_argument("--cpu-devices", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    # a wedged TPU tunnel hangs jax.devices() forever — probe it in a
    # subprocess (the shared watchdog) and force CPU when unreachable
    from __graft_entry__ import _force_cpu, _tpu_reachable

    import jax

    if not _tpu_reachable(timeout_s=150):
        _force_cpu(args.cpu_devices)

    import jax.numpy as jnp
    import numpy as np

    from accl_tpu.models import (
        TransformerConfig,
        init_kv_cache,
        init_params,
        make_decode_step,
    )
    from accl_tpu.models.transformer import shard_params
    from accl_tpu.parallel import make_mesh

    n = len(jax.devices())
    tp = 2 if n % 2 == 0 else 1
    mesh = make_mesh({"dp": n // tp, "sp": 1, "tp": tp},
                     devices=jax.devices())
    cfg = TransformerConfig(vocab=256, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128)
    params = shard_params(init_params(cfg, jax.random.key(args.seed)),
                          cfg, mesh)

    dp = dict(mesh.shape)["dp"]
    B = -(-max(args.batch, 1) // dp) * dp  # round up to a dp multiple
    total = args.prompt_len + args.steps
    rng = np.random.default_rng(args.seed)
    prompt = rng.integers(0, cfg.vocab, (B, args.prompt_len)) \
        .astype(np.int32)

    step = make_decode_step(cfg, mesh)
    cache = init_kv_cache(cfg, mesh, B, max_len=total)
    key = jax.random.key(args.seed + 1)

    toks = prompt
    logits = None
    # prefill token-by-token: the SAME compiled step serves prefill and
    # generation (a fused prefill would be one make_forward call; decode
    # from scratch keeps the demo single-program)
    for t in range(total - 1):
        cur = toks[:, t:t + 1]
        logits, cache = step(params, cache, cur,
                             jnp.array([t], jnp.int32))
        if t >= args.prompt_len - 1:
            if args.temp > 0:
                key, sub = jax.random.split(key)
                nxt = jax.random.categorical(sub, logits[:, 0] / args.temp)
                nxt = np.asarray(nxt, np.int32)[:, None]
            else:
                nxt = np.asarray(jnp.argmax(logits[:, 0], -1),
                                 np.int32)[:, None]
            toks = np.concatenate([toks, nxt], axis=1)

    print(f"mesh={dict(mesh.shape)} prompt_len={args.prompt_len} "
          f"generated={toks.shape[1] - args.prompt_len}")
    for b in range(min(B, 2)):
        print(f"  seq[{b}]: {toks[b].tolist()}")


if __name__ == "__main__":
    main()
