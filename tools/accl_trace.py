#!/usr/bin/env python3
"""Trace exporter / validator CLI for the telemetry subsystem.

Thin client of accl_tpu.telemetry: takes a SPAN v1 trace document
(bench.py --trace writes accl_log/trace.json) and

  --validate            check it against the jsonschema event contract
                        (telemetry.export.EVENT_SCHEMA)
  --chrome OUT          export Chrome trace-event JSON (Perfetto /
                        chrome://tracing loadable, one track per
                        rank/executor)
  --residuals           print the predicted-vs-measured residual table
                        and the default-vs-refit calibration summary
  --metrics             replay the trace through the streaming metrics
                        registry + drift sentinel (the SAME span ->
                        metrics rule the live observer runs,
                        telemetry.metrics.replay_trace) and print the
                        Prometheus exposition, the sentinel verdict,
                        and the straggler report; cross-checks the
                        replayed call counts against a metrics
                        snapshot embedded in the trace meta when one
                        is present (--window sizes the replay
                        sentinel)
  --selftest            run the full contract against the COMMITTED
                        golden trace (accl_log/golden_trace.json):
                        schema validation, Chrome conversion structure,
                        and the feedback-loop invariant (refit link
                        beats the golden trace's embedded default) —
                        the CI telemetry step runs this so the schema
                        and the emitters cannot drift apart silently
  --make-golden         regenerate the golden trace (deterministic
                        synthetic spans; run after an intentional
                        schema change and commit the result)

Exit code 0 = every requested check passed.
"""

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

GOLDEN = REPO / "accl_log" / "golden_trace.json"

# sentinel window for the golden trace's drift segment (16 stable +
# 12 shifted alltoall spans): small enough that the shifted tail owns
# the rolling median, the regression the selftest pins
GOLDEN_SENTINEL_WINDOW = 16


def make_golden() -> dict:
    """Deterministic synthetic trace exercising every span category the
    emitters produce: facade calls, sequence + phases + steps, and
    native per-rank spans whose measurements follow a known link
    (alpha=120us, beta=0.8 GB/s) with deterministic multiplicative
    skew — so calibrate_from_trace provably recovers a better fit than
    the 'shipped default' embedded in meta."""
    from accl_tpu.telemetry.tracer import SCHEMA_VERSION

    spans = []
    t = 1_000_000
    # facade call + sequence machinery spans
    spans.append({"name": "allreduce", "cat": "call", "track": "facade",
                  "ts_ns": t, "dur_ns": 2_000_000,
                  "args": {"op": "allreduce", "count": 4096,
                           "algorithm": "EAGER_RING_RS_AG",
                           "predicted_s": 0.0019, "retcode": 0}})
    sig = "deadbeefcafef00d"
    for name, dur in (("record", 50_000), ("lint", 400_000),
                      ("compile", 3_000_000), ("dispatch", 1_500_000)):
        t += 100_000
        spans.append({"name": name, "cat": "phase", "track": "device",
                      "ts_ns": t, "dur_ns": dur,
                      "args": {"signature": sig}})
    for i, op in enumerate(("reduce_scatter", "allgather")):
        spans.append({"name": f"step{i}:{op}", "cat": "step",
                      "track": "device", "ts_ns": t, "dur_ns": 0,
                      "args": {"op": op, "step": i, "signature": sig,
                               "predicted_s": 0.001 * (i + 1)}})
    spans.append({"name": "sequence", "cat": "sequence", "track": "facade",
                  "ts_ns": t, "dur_ns": 6_000_000,
                  "args": {"n_steps": 2, "signature": sig,
                           "predicted_s": 0.003}})
    # native spans: measured = true_link(m, b) * skew, skew cycling over
    # a fixed pattern; the golden default is deliberately off by 2x beta
    alpha, beta = 120e-6, 0.8e9
    default = {"alpha_us": 40.0, "beta_gbps": 2.4}
    skews = (0.9, 1.0, 1.1, 1.05, 0.95)
    k = 0
    for rank in range(4):
        t0 = 2_000_000
        for m, b in ((8.0, 65536.0), (16.0, 262144.0), (32.0, 2097152.0),
                     (64.0, 8388608.0)):
            true_s = alpha * m + b / beta
            meas = true_s * skews[k % len(skews)]
            k += 1
            dur = int(meas * 1e9)
            spans.append({
                "name": "allreduce", "cat": "native",
                "track": f"emu/r{rank}", "ts_ns": t0, "dur_ns": dur,
                "args": {"op": "allreduce", "count": int(b // 4),
                         "bytes": int(b), "world": 4, "rank": rank,
                         "retcode": 0, "detail": 0,
                         "measured_s": meas,
                         "coef_messages": m, "coef_bytes": b,
                         "predicted_s": default["alpha_us"] * 1e-6 * m
                         + b / (default["beta_gbps"] * 1e9),
                         "d_passes": 4, "d_parks": 3,
                         "d_seek_hit": 4, "d_seek_miss": 3}})
            t0 += dur + 50_000
    # tier-tagged native spans (args["tier"], SPAN v1-compatible detail
    # key): two tiers with DELIBERATELY different true links, so the
    # selftest can prove calibrate_tiers_from_trace recovers each from
    # exactly its own labeled samples (an unlabeled/pooled fit would
    # average them)
    tier_true = {"inner": (2e-6, 4.0e9), "outer": (200e-6, 0.1e9)}
    for tier, (ta, tb) in tier_true.items():
        t0 = 40_000_000
        for rank in range(2):
            for m, b in ((4.0, 131072.0), (8.0, 1048576.0),
                         (16.0, 4194304.0)):
                meas = (ta * m + b / tb) * skews[k % len(skews)]
                k += 1
                dur = int(meas * 1e9)
                spans.append({
                    "name": "reduce_scatter" if tier == "inner"
                    else "allreduce",
                    "cat": "native", "track": f"hier/{tier}/r{rank}",
                    "ts_ns": t0, "dur_ns": dur,
                    "args": {"op": "reduce_scatter" if tier == "inner"
                             else "allreduce",
                             "count": int(b // 4), "bytes": int(b),
                             "world": 4, "rank": rank, "tier": tier,
                             "retcode": 0, "detail": 0,
                             "measured_s": meas,
                             "coef_messages": m, "coef_bytes": b,
                             "d_passes": 2, "d_parks": 1,
                             "d_seek_hit": 2, "d_seek_miss": 1}})
                t0 += dur + 50_000
    # drift-sentinel segment (op "alltoall", used by no other golden
    # span): ACCURATE predictions in the stable regime — rank 3 runs a
    # deliberate 1.5x slow (the straggler the per-rank attribution must
    # name) — then a 4x regime shift under the SAME stale prediction.
    # No coef_* keys: these spans demo the band-leave verdict and must
    # not contaminate the calibration-invariant sample set above.
    at_true, at_count = 3e-3, 8192
    jit = (0.97, 1.0, 1.03)
    t0 = 80_000_000
    at_spans = []
    for wave in range(4):  # stable regime: 4 waves x 4 ranks
        for rank in range(4):
            meas = at_true * (1.5 if rank == 3 else 1.0) \
                * jit[(wave + rank) % len(jit)]
            at_spans.append((rank, meas, "stable"))
    for wave in range(3):  # regime shift: 3 waves x 4 ranks, 4x slower
        for rank in range(4):
            meas = at_true * 4.0 * jit[(wave + rank) % len(jit)]
            at_spans.append((rank, meas, "shifted"))
    for rank, meas, regime in at_spans:
        dur = int(meas * 1e9)
        spans.append({
            "name": "alltoall", "cat": "native",
            "track": f"emu/r{rank}", "ts_ns": t0, "dur_ns": dur,
            "args": {"op": "alltoall", "count": at_count,
                     "bytes": at_count * 4, "world": 4, "rank": rank,
                     "retcode": 0, "detail": 0, "measured_s": meas,
                     "predicted_s": at_true, "regime": regime,
                     "d_passes": 1, "d_parks": 0,
                     "d_seek_hit": 1, "d_seek_miss": 0}})
        t0 += dur + 25_000
    meta = {"golden": True, "drops": 0,
            "default_link": default,
            "sentinel_window": GOLDEN_SENTINEL_WINDOW,
            "tier_true_links": {
                t: {"alpha_us": a * 1e6, "beta_gbps": bb / 1e9}
                for t, (a, bb) in tier_true.items()}}
    # embed the metrics snapshot + sentinel report the always-on layer
    # would serve for exactly these spans (Tracer.to_trace's posture),
    # so --selftest covers the meta keys every exported trace now ships
    from accl_tpu.telemetry.metrics import (
        DriftSentinel,
        MetricsObserver,
        MetricsRegistry,
        replay_trace,
    )

    obs = replay_trace({"spans": spans}, MetricsObserver(
        MetricsRegistry(), DriftSentinel(window=GOLDEN_SENTINEL_WINDOW)))
    meta.update(obs.trace_meta())
    return {"schema": SCHEMA_VERSION, "meta": meta, "spans": spans}


def cmd_validate(trace: dict) -> None:
    from accl_tpu.telemetry import validate_trace

    validate_trace(trace)
    print(f"schema OK: {len(trace['spans'])} spans, "
          f"{len({s['track'] for s in trace['spans']})} tracks")


def cmd_chrome(trace: dict, out: str) -> None:
    from accl_tpu.telemetry import to_chrome

    chrome = to_chrome(trace)
    pathlib.Path(out).write_text(json.dumps(chrome, indent=1))
    print(f"wrote {out} ({len(chrome['traceEvents'])} events)")


def cmd_metrics(trace: dict, window: int) -> int:
    """Replay a trace through the metrics registry + drift sentinel
    and print what the always-on layer would be serving live."""
    from accl_tpu.telemetry.metrics import (
        DriftSentinel,
        MetricsObserver,
        MetricsRegistry,
        replay_trace,
    )

    obs = replay_trace(trace, MetricsObserver(
        MetricsRegistry(), DriftSentinel(window=window)))
    text = obs.registry.expose_text()
    print(text, end="")
    rep = obs.sentinel.report()
    flagged = rep["flagged"]
    print(f"drift sentinel (window {window}): "
          f"{len(rep['verdict'])} op(s), flagged={flagged or 'none'}")
    for op, row in rep["verdict"].items():
        band = (f" band<={row['band_hi']:.3f} "
                f"{'OUT-OF-BAND' if not row['in_band'] else 'in band'}"
                if row.get("armed") else " (unarmed)")
        print(f"  {op:20s} n={row['n']:<4d} median rel err "
              f"{row['median_rel_err']:.3f}{band}")
    for w in rep["stragglers"]:
        print(f"  straggler {w['op']}/{w['count']}: rank "
              f"{w['straggler_rank']} at {w['skew']:.2f}x the "
              f"median-of-ranks ({w['ranks']} ranks)")
    embedded = trace.get("meta", {}).get("metrics")
    if embedded is not None:
        # the snapshot embedded at export time and this offline replay
        # run the same rule: their call counts must agree, or the
        # emitters and the replay path have drifted apart
        def total(snap):
            return sum(r["value"] for r in
                       snap.get("counters", {}).get("accl_calls_total", []))

        got = total(obs.registry.snapshot())
        want = total(embedded)
        if got != want:
            print(f"FAIL: replayed call count {got:g} != embedded "
                  f"snapshot {want:g}", file=sys.stderr)
            return 1
        print(f"embedded snapshot cross-check OK ({got:g} calls)")
    return 0


def cmd_residuals(trace: dict) -> None:
    from accl_tpu.telemetry import residual_report

    report = residual_report(trace)
    sr = report["span_residuals"]
    med = sr["median_rel_err"]
    print(f"spans with predictions: {sr['rows']}  "
          f"median |pred-meas|/meas: "
          f"{'n/a' if med is None else f'{med:.3f}'}")
    for op, err in sr["per_op_median_rel_err"].items():
        print(f"  {op:20s} {err:.3f}")
    cal = report["calibration"]
    if "error" in cal:
        print(f"calibration: {cal['error']}")
    else:
        print(f"calibration over {cal['samples']} samples: refit alpha "
              f"{cal['refit']['alpha_us']:.1f} us beta "
              f"{cal['refit']['beta_gbps']:.3f} GB/s -> median rel err "
              f"{cal['median_rel_err_refit']:.3f}"
              + (f" (default {cal['median_rel_err_default']:.3f}, "
                 f"improved={cal['improved']})"
                 if "median_rel_err_default" in cal else ""))


def cmd_selftest() -> int:
    """The committed-golden contract: schema, Chrome structure, residual
    machinery, and the feedback-loop invariant."""
    from accl_tpu.sequencer.timing import LinkParams
    from accl_tpu.telemetry import (calibrate_from_trace, residual_rows,
                                    to_chrome, validate_trace)
    from accl_tpu.telemetry.export import median
    from accl_tpu.telemetry.feedback import _rel_errs

    if not GOLDEN.exists():
        print(f"FAIL: no committed golden trace at {GOLDEN}",
              file=sys.stderr)
        return 1
    trace = json.loads(GOLDEN.read_text())
    validate_trace(trace)
    chrome = to_chrome(trace)
    names = [e for e in chrome["traceEvents"] if e["ph"] == "M"]
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert len(names) == len({s["track"] for s in trace["spans"]}), \
        "one thread_name metadata event per track"
    assert len(xs) == len(trace["spans"]), "one X event per span"
    assert all(e["dur"] > 0 for e in xs), "zero-duration spans stretched"
    rows = residual_rows(trace)
    assert rows, "golden trace must carry predicted-vs-measured rows"
    # feedback-loop invariant: refitting on the golden measurements beats
    # the deliberately-skewed default link embedded in its meta
    d = trace["meta"]["default_link"]
    default = LinkParams(alpha=d["alpha_us"] * 1e-6,
                         beta=d["beta_gbps"] * 1e9)
    refit = calibrate_from_trace(trace)
    e_ref = median(_rel_errs(trace, refit))
    e_def = median(_rel_errs(trace, default))
    assert e_ref < e_def, \
        f"refit {e_ref:.3f} must beat golden default {e_def:.3f}"
    # tier-tagged spans (args["tier"]): Chrome tracks split by tier and
    # the per-tier refit recovers each tier's DISTINCT true link from
    # exactly its own labeled samples — a pooled (unlabeled) fit would
    # average the fast and slow tiers together
    from accl_tpu.telemetry import calibrate_tiers_from_trace

    tier_tracks = {s["track"] for s in trace["spans"]
                   if s["args"].get("tier")}
    assert any("inner" in t for t in tier_tracks) and \
        any("outer" in t for t in tier_tracks), \
        "golden trace must carry tier-tagged spans on split tracks"
    tiers = calibrate_tiers_from_trace(trace)
    true = trace["meta"]["tier_true_links"]
    for tier, fit in (("inner", tiers.inner), ("outer", tiers.outer)):
        want = true[tier]["beta_gbps"] * 1e9
        assert abs(fit.beta - want) / want < 0.25, \
            f"{tier} refit beta {fit.beta / 1e9:.2f} GB/s far from " \
            f"true {want / 1e9:.2f}"
    assert tiers.inner.beta > 10 * tiers.outer.beta, \
        "per-tier refit must keep the fast and slow links apart"
    # the always-on observability meta keys: the committed golden must
    # carry the metrics snapshot + sentinel report, the offline replay
    # must reproduce them (same rule, no drift), and the sentinel must
    # FLAG the embedded regime shift while attributing the deliberate
    # rank-3 straggler — the sensing contract, pinned on committed data
    from accl_tpu.telemetry.metrics import (
        DriftSentinel,
        MetricsObserver,
        MetricsRegistry,
        replay_trace,
    )

    assert "metrics" in trace["meta"] and "drift_sentinel" in \
        trace["meta"], "golden meta must embed the observability keys"
    win = int(trace["meta"]["sentinel_window"])
    obs = replay_trace(trace, MetricsObserver(
        MetricsRegistry(), DriftSentinel(window=win)))
    def _calls(snap):
        return sum(r["value"] for r in
                   snap.get("counters", {}).get("accl_calls_total", []))
    assert _calls(obs.registry.snapshot()) == \
        _calls(trace["meta"]["metrics"]), \
        "offline metrics replay diverged from the embedded snapshot"
    flagged = obs.sentinel.flagged()
    assert flagged == ["alltoall"], \
        f"sentinel must flag exactly the shifted op, got {flagged}"
    v = obs.sentinel.verdict()["alltoall"]
    assert not v["in_band"] and v["median_rel_err"] > v["band_hi"]
    embedded_flags = trace["meta"]["drift_sentinel"]["flagged"]
    assert embedded_flags == ["alltoall"], \
        "embedded sentinel report must carry the same verdict"
    strag = [w for w in obs.sentinel.straggler_report()
             if w["op"] == "alltoall"]
    assert strag and strag[0]["straggler_rank"] == 3 and \
        strag[0]["skew"] > 1.2, \
        "per-rank attribution must name the deliberate rank-3 straggler"
    print(f"selftest OK: {len(trace['spans'])} golden spans, "
          f"{len(names)} tracks, refit median rel err {e_ref:.3f} < "
          f"default {e_def:.3f}; tier refit inner "
          f"{tiers.inner.beta / 1e9:.2f} GB/s / outer "
          f"{tiers.outer.beta / 1e9:.3f} GB/s; sentinel flagged "
          f"{flagged} (straggler r{strag[0]['straggler_rank']} at "
          f"{strag[0]['skew']:.2f}x)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?",
                    default=str(REPO / "accl_log" / "trace.json"))
    ap.add_argument("--validate", action="store_true")
    ap.add_argument("--chrome", metavar="OUT")
    ap.add_argument("--residuals", action="store_true")
    ap.add_argument("--metrics", action="store_true")
    ap.add_argument("--window", type=int, default=GOLDEN_SENTINEL_WINDOW,
                    help="drift-sentinel rolling window for --metrics "
                         "replay (default %(default)s)")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--make-golden", action="store_true")
    args = ap.parse_args()

    if args.make_golden:
        from accl_tpu.telemetry import validate_trace

        trace = make_golden()
        validate_trace(trace)
        GOLDEN.write_text(json.dumps(trace, indent=1))
        print(f"wrote {GOLDEN} ({len(trace['spans'])} spans)")
        return 0
    if args.selftest:
        return cmd_selftest()

    trace = json.loads(pathlib.Path(args.trace).read_text())
    ran = False
    if args.validate or not (args.chrome or args.residuals
                             or args.metrics):
        cmd_validate(trace)
        ran = True
    if args.chrome:
        cmd_chrome(trace, args.chrome)
        ran = True
    if args.residuals:
        cmd_residuals(trace)
        ran = True
    if args.metrics:
        rc = cmd_metrics(trace, args.window)
        if rc:
            return rc
        ran = True
    return 0 if ran else 2


if __name__ == "__main__":
    sys.exit(main())
