#!/usr/bin/env python3
"""Trace exporter / validator CLI for the telemetry subsystem.

Thin client of accl_tpu.telemetry: takes a SPAN v1 trace document
(bench.py --trace writes accl_log/trace.json) and

  --validate            check it against the jsonschema event contract
                        (telemetry.export.EVENT_SCHEMA)
  --chrome OUT          export Chrome trace-event JSON (Perfetto /
                        chrome://tracing loadable, one track per
                        rank/executor)
  --residuals           print the predicted-vs-measured residual table
                        and the default-vs-refit calibration summary
  --selftest            run the full contract against the COMMITTED
                        golden trace (accl_log/golden_trace.json):
                        schema validation, Chrome conversion structure,
                        and the feedback-loop invariant (refit link
                        beats the golden trace's embedded default) —
                        the CI telemetry step runs this so the schema
                        and the emitters cannot drift apart silently
  --make-golden         regenerate the golden trace (deterministic
                        synthetic spans; run after an intentional
                        schema change and commit the result)

Exit code 0 = every requested check passed.
"""

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

GOLDEN = REPO / "accl_log" / "golden_trace.json"


def make_golden() -> dict:
    """Deterministic synthetic trace exercising every span category the
    emitters produce: facade calls, sequence + phases + steps, and
    native per-rank spans whose measurements follow a known link
    (alpha=120us, beta=0.8 GB/s) with deterministic multiplicative
    skew — so calibrate_from_trace provably recovers a better fit than
    the 'shipped default' embedded in meta."""
    from accl_tpu.telemetry.tracer import SCHEMA_VERSION

    spans = []
    t = 1_000_000
    # facade call + sequence machinery spans
    spans.append({"name": "allreduce", "cat": "call", "track": "facade",
                  "ts_ns": t, "dur_ns": 2_000_000,
                  "args": {"op": "allreduce", "count": 4096,
                           "algorithm": "EAGER_RING_RS_AG",
                           "predicted_s": 0.0019, "retcode": 0}})
    sig = "deadbeefcafef00d"
    for name, dur in (("record", 50_000), ("lint", 400_000),
                      ("compile", 3_000_000), ("dispatch", 1_500_000)):
        t += 100_000
        spans.append({"name": name, "cat": "phase", "track": "device",
                      "ts_ns": t, "dur_ns": dur,
                      "args": {"signature": sig}})
    for i, op in enumerate(("reduce_scatter", "allgather")):
        spans.append({"name": f"step{i}:{op}", "cat": "step",
                      "track": "device", "ts_ns": t, "dur_ns": 0,
                      "args": {"op": op, "step": i, "signature": sig,
                               "predicted_s": 0.001 * (i + 1)}})
    spans.append({"name": "sequence", "cat": "sequence", "track": "facade",
                  "ts_ns": t, "dur_ns": 6_000_000,
                  "args": {"n_steps": 2, "signature": sig,
                           "predicted_s": 0.003}})
    # native spans: measured = true_link(m, b) * skew, skew cycling over
    # a fixed pattern; the golden default is deliberately off by 2x beta
    alpha, beta = 120e-6, 0.8e9
    default = {"alpha_us": 40.0, "beta_gbps": 2.4}
    skews = (0.9, 1.0, 1.1, 1.05, 0.95)
    k = 0
    for rank in range(4):
        t0 = 2_000_000
        for m, b in ((8.0, 65536.0), (16.0, 262144.0), (32.0, 2097152.0),
                     (64.0, 8388608.0)):
            true_s = alpha * m + b / beta
            meas = true_s * skews[k % len(skews)]
            k += 1
            dur = int(meas * 1e9)
            spans.append({
                "name": "allreduce", "cat": "native",
                "track": f"emu/r{rank}", "ts_ns": t0, "dur_ns": dur,
                "args": {"op": "allreduce", "count": int(b // 4),
                         "bytes": int(b), "world": 4, "rank": rank,
                         "retcode": 0, "detail": 0,
                         "measured_s": meas,
                         "coef_messages": m, "coef_bytes": b,
                         "predicted_s": default["alpha_us"] * 1e-6 * m
                         + b / (default["beta_gbps"] * 1e9),
                         "d_passes": 4, "d_parks": 3,
                         "d_seek_hit": 4, "d_seek_miss": 3}})
            t0 += dur + 50_000
    # tier-tagged native spans (args["tier"], SPAN v1-compatible detail
    # key): two tiers with DELIBERATELY different true links, so the
    # selftest can prove calibrate_tiers_from_trace recovers each from
    # exactly its own labeled samples (an unlabeled/pooled fit would
    # average them)
    tier_true = {"inner": (2e-6, 4.0e9), "outer": (200e-6, 0.1e9)}
    for tier, (ta, tb) in tier_true.items():
        t0 = 40_000_000
        for rank in range(2):
            for m, b in ((4.0, 131072.0), (8.0, 1048576.0),
                         (16.0, 4194304.0)):
                meas = (ta * m + b / tb) * skews[k % len(skews)]
                k += 1
                dur = int(meas * 1e9)
                spans.append({
                    "name": "reduce_scatter" if tier == "inner"
                    else "allreduce",
                    "cat": "native", "track": f"hier/{tier}/r{rank}",
                    "ts_ns": t0, "dur_ns": dur,
                    "args": {"op": "reduce_scatter" if tier == "inner"
                             else "allreduce",
                             "count": int(b // 4), "bytes": int(b),
                             "world": 4, "rank": rank, "tier": tier,
                             "retcode": 0, "detail": 0,
                             "measured_s": meas,
                             "coef_messages": m, "coef_bytes": b,
                             "d_passes": 2, "d_parks": 1,
                             "d_seek_hit": 2, "d_seek_miss": 1}})
                t0 += dur + 50_000
    return {"schema": SCHEMA_VERSION,
            "meta": {"golden": True, "drops": 0,
                     "default_link": default,
                     "tier_true_links": {
                         t: {"alpha_us": a * 1e6, "beta_gbps": bb / 1e9}
                         for t, (a, bb) in tier_true.items()}},
            "spans": spans}


def cmd_validate(trace: dict) -> None:
    from accl_tpu.telemetry import validate_trace

    validate_trace(trace)
    print(f"schema OK: {len(trace['spans'])} spans, "
          f"{len({s['track'] for s in trace['spans']})} tracks")


def cmd_chrome(trace: dict, out: str) -> None:
    from accl_tpu.telemetry import to_chrome

    chrome = to_chrome(trace)
    pathlib.Path(out).write_text(json.dumps(chrome, indent=1))
    print(f"wrote {out} ({len(chrome['traceEvents'])} events)")


def cmd_residuals(trace: dict) -> None:
    from accl_tpu.telemetry import residual_report

    report = residual_report(trace)
    sr = report["span_residuals"]
    print(f"spans with predictions: {sr['rows']}  "
          f"median |pred-meas|/meas: {sr['median_rel_err']:.3f}")
    for op, err in sr["per_op_median_rel_err"].items():
        print(f"  {op:20s} {err:.3f}")
    cal = report["calibration"]
    if "error" in cal:
        print(f"calibration: {cal['error']}")
    else:
        print(f"calibration over {cal['samples']} samples: refit alpha "
              f"{cal['refit']['alpha_us']:.1f} us beta "
              f"{cal['refit']['beta_gbps']:.3f} GB/s -> median rel err "
              f"{cal['median_rel_err_refit']:.3f}"
              + (f" (default {cal['median_rel_err_default']:.3f}, "
                 f"improved={cal['improved']})"
                 if "median_rel_err_default" in cal else ""))


def cmd_selftest() -> int:
    """The committed-golden contract: schema, Chrome structure, residual
    machinery, and the feedback-loop invariant."""
    from accl_tpu.sequencer.timing import LinkParams
    from accl_tpu.telemetry import (calibrate_from_trace, residual_rows,
                                    to_chrome, validate_trace)
    from accl_tpu.telemetry.export import median
    from accl_tpu.telemetry.feedback import _rel_errs

    if not GOLDEN.exists():
        print(f"FAIL: no committed golden trace at {GOLDEN}",
              file=sys.stderr)
        return 1
    trace = json.loads(GOLDEN.read_text())
    validate_trace(trace)
    chrome = to_chrome(trace)
    names = [e for e in chrome["traceEvents"] if e["ph"] == "M"]
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert len(names) == len({s["track"] for s in trace["spans"]}), \
        "one thread_name metadata event per track"
    assert len(xs) == len(trace["spans"]), "one X event per span"
    assert all(e["dur"] > 0 for e in xs), "zero-duration spans stretched"
    rows = residual_rows(trace)
    assert rows, "golden trace must carry predicted-vs-measured rows"
    # feedback-loop invariant: refitting on the golden measurements beats
    # the deliberately-skewed default link embedded in its meta
    d = trace["meta"]["default_link"]
    default = LinkParams(alpha=d["alpha_us"] * 1e-6,
                         beta=d["beta_gbps"] * 1e9)
    refit = calibrate_from_trace(trace)
    e_ref = median(_rel_errs(trace, refit))
    e_def = median(_rel_errs(trace, default))
    assert e_ref < e_def, \
        f"refit {e_ref:.3f} must beat golden default {e_def:.3f}"
    # tier-tagged spans (args["tier"]): Chrome tracks split by tier and
    # the per-tier refit recovers each tier's DISTINCT true link from
    # exactly its own labeled samples — a pooled (unlabeled) fit would
    # average the fast and slow tiers together
    from accl_tpu.telemetry import calibrate_tiers_from_trace

    tier_tracks = {s["track"] for s in trace["spans"]
                   if s["args"].get("tier")}
    assert any("inner" in t for t in tier_tracks) and \
        any("outer" in t for t in tier_tracks), \
        "golden trace must carry tier-tagged spans on split tracks"
    tiers = calibrate_tiers_from_trace(trace)
    true = trace["meta"]["tier_true_links"]
    for tier, fit in (("inner", tiers.inner), ("outer", tiers.outer)):
        want = true[tier]["beta_gbps"] * 1e9
        assert abs(fit.beta - want) / want < 0.25, \
            f"{tier} refit beta {fit.beta / 1e9:.2f} GB/s far from " \
            f"true {want / 1e9:.2f}"
    assert tiers.inner.beta > 10 * tiers.outer.beta, \
        "per-tier refit must keep the fast and slow links apart"
    print(f"selftest OK: {len(trace['spans'])} golden spans, "
          f"{len(names)} tracks, refit median rel err {e_ref:.3f} < "
          f"default {e_def:.3f}; tier refit inner "
          f"{tiers.inner.beta / 1e9:.2f} GB/s / outer "
          f"{tiers.outer.beta / 1e9:.3f} GB/s")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?",
                    default=str(REPO / "accl_log" / "trace.json"))
    ap.add_argument("--validate", action="store_true")
    ap.add_argument("--chrome", metavar="OUT")
    ap.add_argument("--residuals", action="store_true")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--make-golden", action="store_true")
    args = ap.parse_args()

    if args.make_golden:
        from accl_tpu.telemetry import validate_trace

        trace = make_golden()
        validate_trace(trace)
        GOLDEN.write_text(json.dumps(trace, indent=1))
        print(f"wrote {GOLDEN} ({len(trace['spans'])} spans)")
        return 0
    if args.selftest:
        return cmd_selftest()

    trace = json.loads(pathlib.Path(args.trace).read_text())
    ran = False
    if args.validate or not (args.chrome or args.residuals):
        cmd_validate(trace)
        ran = True
    if args.chrome:
        cmd_chrome(trace, args.chrome)
        ran = True
    if args.residuals:
        cmd_residuals(trace)
        ran = True
    return 0 if ran else 2


if __name__ == "__main__":
    sys.exit(main())
