#!/usr/bin/env python3
"""Per-config sequencer-counter sweep — a thin client of the telemetry
subsystem.

VERDICT r4 asked for data, not guesses, on where the eager ring
collectives spend their 2(P-1) hops. This driver runs ONE
(collective, bytes, world, transport) config per child process with the
device-resident trace ring armed (ACCL_RT_TRACE=1): the child drains
each rank's live counters (EmuRank.sequencer_stats) and per-call spans
(EmuRank.trace_read -> telemetry.native), and reports structured JSON —
no stderr regex scraping. The parent writes accl_log/rt_stats.csv with
the measured per-call seconds, the counter totals, AND the aggregate
wire-bytes bandwidth (timing.coefficients_aggregate volume / measured
seconds — the volume-honest column the r5 verdict asked for: payload
GB/s understates collectives that move (P-1)x their payload).

Run before and after a data-plane change; commit the CSV with the sweep
it explains.
"""

import argparse
import csv
import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]

# The child: one config per process (ACCL_RT_SHAPE/ACCL_RT_TRACE are
# read at runtime creation, so per-config env needs process isolation).
# Reports ONE JSON line on stdout: per-rank counters, per-call seconds,
# and the drained native spans in SPAN v1 shape.
CHILD = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, sys.argv[1])
from accl_tpu import ReduceFunction
from accl_tpu.device.emu_device import EmuWorld
from accl_tpu.telemetry import native as tnative

name, transport = sys.argv[2], sys.argv[5]
nbytes, world, iters = int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[6])
count = nbytes // 4
w = EmuWorld(world, max_eager=tnative.DEFAULT_MAX_EAGER,
             rx_buf_bytes=tnative.DEFAULT_RX_BUF, transport=transport)
try:
    def body(rank, i):
        x = np.ones(count, np.float32)
        out = np.zeros(count * (world if name == "allgather" else 1),
                       np.float32)
        rank.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            if name == "allreduce":
                rank.allreduce(x, out, count, ReduceFunction.SUM)
            elif name == "bcast":
                rank.bcast(x, count, root=0)
            elif name == "reduce":
                rank.reduce(x, out, count, 0, ReduceFunction.SUM)
            elif name == "gather":
                gout = np.zeros(count * world, np.float32)
                rank.gather(x, gout, count, 0)
            elif name == "reduce_scatter":
                rsout = np.zeros(max(count // world, 1), np.float32)
                rank.reduce_scatter(x, rsout, max(count // world, 1),
                                    ReduceFunction.SUM)
            else:
                rank.allgather(x, out, count)
        return (time.perf_counter() - t0) / iters
    secs = max(w.run(body))
    stats = [r.sequencer_stats() for r in w.ranks]
    spans, dropped = tnative.drain_world(w)
    print(json.dumps({
        "seconds": secs,
        "stats": stats,
        "spans": len(spans),
        "span_dropped": dropped,
        "retcodes": sorted({s["args"]["retcode"] for s in spans}),
    }))
finally:
    w.close()
"""


def run_config(name, nbytes, world, transport, iters, shape=""):
    import os

    env = dict(os.environ)
    env["ACCL_RT_TRACE"] = "1"
    r = subprocess.run([sys.executable, "-c", CHILD, str(REPO), name,
                        str(nbytes), str(world), transport, str(iters)],
                       env=env, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print(f"  {name} {nbytes}B w{world} {transport}: FAILED\n"
              f"{r.stderr[-2000:]}", file=sys.stderr)
        return None
    payload = None
    for line in r.stdout.splitlines():
        try:
            payload = json.loads(line)
            break
        except ValueError:
            continue
    if payload is None:
        print(f"  {name} {nbytes}B w{world}: no JSON report parsed",
              file=sys.stderr)
        return None
    secs = payload["seconds"]
    # aggregate across ranks: totals tell the story (parks and seek
    # misses are the per-hop fixed costs; park_ms the latency paid)
    tot = [sum(st[k] for st in payload["stats"])
           for k in ("passes", "parks", "park_ns", "seek_hit",
                     "seek_miss")]
    tot[2] = tot[2] / 1e6  # park_ns -> park_ms (the CSV's historic unit)
    from accl_tpu.telemetry.native import aggregate_wire_gbps

    # mirror a forced ACCL_RT_SHAPE into the cost computation so the
    # coefficients describe the schedule that actually ran. For the
    # bandwidth-optimal logp/ring pair the aggregate BYTES coincide, so
    # this column happens to be shape-invariant — the mirror keeps it
    # honest by construction (and exact if a non-volume-equal shape is
    # ever added) rather than by coincidence
    logp_shape = {"": None, "ring": False, "logp": True}[shape]
    agg_gbps = aggregate_wire_gbps(name, nbytes, world, secs,
                                   logp_shape=logp_shape)
    return (name, nbytes, world, transport, iters, secs, *tot, agg_gbps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="rt_stats.csv")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--transport", default="tcp",
                    choices=("tcp", "udp", "local"))
    ap.add_argument("--worlds", default="8")
    ap.add_argument("--collectives", default="allreduce,bcast,allgather")
    ap.add_argument("--sizes", default="65536,1048576,4194304")
    ap.add_argument("--shape", default="", choices=("", "ring", "logp"),
                    help="force the allreduce/allgather hop shape via "
                         "ACCL_RT_SHAPE in the child (crossover "
                         "calibration)")
    args = ap.parse_args()

    import os

    if args.shape:
        os.environ["ACCL_RT_SHAPE"] = args.shape

    sys.path.insert(0, str(REPO))
    rows = []
    for world in [int(w) for w in args.worlds.split(",")]:
        for name in args.collectives.split(","):
            for nbytes in [int(s) for s in args.sizes.split(",")]:
                row = run_config(name, nbytes, world, args.transport,
                                 args.iters, shape=args.shape)
                if row:
                    rows.append(row)
                    (n, b, w, t, it, s, passes, parks, park_ms, hit,
                     miss, agg) = row
                    print(f"  {n:13s} {b:>9d}B w{w} {s*1e3:9.2f} ms/call"
                          f"  passes={passes} parks={parks}"
                          f" park_ms={park_ms:.1f} seek_hit={hit}"
                          f" seek_miss={miss} aggwire={agg:.3f} GB/s",
                          file=sys.stderr)

    out = REPO / "accl_log" / args.out
    shape = args.shape or "auto"
    with open(out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["Collective", "Bytes", "World", "Transport", "Iters",
                    "SecondsPerCall", "Passes", "Parks", "ParkMs",
                    "SeekHit", "SeekMiss", "AggWireGBps", "Shape"])
        w.writerows([(*r[:-1], f"{r[-1]:.4f}", shape) for r in rows])
    print(f"wrote {out} ({len(rows)} rows)")


if __name__ == "__main__":
    main()
