#!/usr/bin/env python3
"""Per-config ACCL_RT_STATS counter sweep against the native emulator.

VERDICT r4 asked for data, not guesses, on where the eager ring
collectives spend their 2(P-1) hops: this driver runs ONE
(collective, bytes, world, transport) config per child process with
ACCL_RT_STATS=1, parses each rank runtime's counter line
(passes/parks/park_ms/seek_hit/seek_miss, printed at destroy,
native/src/runtime.cpp), and writes accl_log/rt_stats.csv with the
measured per-call seconds alongside — so a regression or a fix shows up
as counters AND time in the same row.

Run before and after a data-plane change; commit the CSV with the sweep
it explains.
"""

import argparse
import csv
import pathlib
import re
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]

CHILD = r"""
import sys, time
import numpy as np
sys.path.insert(0, sys.argv[1])
from accl_tpu import ReduceFunction
from accl_tpu.device.emu_device import EmuWorld

name, transport = sys.argv[2], sys.argv[5]
nbytes, world, iters = int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[6])
count = nbytes // 4
w = EmuWorld(world, max_eager=4096, rx_buf_bytes=4096, transport=transport)
try:
    def body(rank, i):
        x = np.ones(count, np.float32)
        out = np.zeros(count * (world if name == "allgather" else 1),
                       np.float32)
        rank.barrier()
        t0 = time.perf_counter()
        for _ in range(iters):
            if name == "allreduce":
                rank.allreduce(x, out, count, ReduceFunction.SUM)
            elif name == "bcast":
                rank.bcast(x, count, root=0)
            elif name == "reduce":
                rank.reduce(x, out, count, 0, ReduceFunction.SUM)
            elif name == "gather":
                gout = np.zeros(count * world, np.float32)
                rank.gather(x, gout, count, 0)
            elif name == "reduce_scatter":
                rsout = np.zeros(max(count // world, 1), np.float32)
                rank.reduce_scatter(x, rsout, max(count // world, 1),
                                    ReduceFunction.SUM)
            else:
                rank.allgather(x, out, count)
        return (time.perf_counter() - t0) / iters
    secs = max(w.run(body))
    print(f"SECONDS {secs:.6e}", file=sys.stderr)
finally:
    w.close()
"""

STAT_RE = re.compile(
    r"\[r(\d+)\] stats: passes=(\d+) parks=(\d+) park_ms=([\d.]+) "
    r"seek_hit=(\d+) seek_miss=(\d+)")


def run_config(name, nbytes, world, transport, iters):
    import os

    env = dict(os.environ)
    env["ACCL_RT_STATS"] = "1"
    r = subprocess.run([sys.executable, "-c", CHILD, str(REPO), name,
                        str(nbytes), str(world), transport, str(iters)],
                       env=env, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print(f"  {name} {nbytes}B w{world} {transport}: FAILED\n"
              f"{r.stderr[-2000:]}", file=sys.stderr)
        return None
    secs = None
    ranks = []
    for line in r.stderr.splitlines():
        m = STAT_RE.search(line)
        if m:
            ranks.append(tuple(int(x) if i != 3 else float(x)
                               for i, x in enumerate(m.groups())))
        elif line.startswith("SECONDS"):
            secs = float(line.split()[1])
    if secs is None or not ranks:
        print(f"  {name} {nbytes}B w{world}: no stats parsed",
              file=sys.stderr)
        return None
    # aggregate across ranks: totals tell the story (parks and seek
    # misses are the per-hop fixed costs; park_ms the latency paid)
    tot = [sum(r[i] for r in ranks) for i in range(1, 6)]
    return (name, nbytes, world, transport, iters, secs, *tot)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="rt_stats.csv")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--transport", default="tcp",
                    choices=("tcp", "udp", "local"))
    ap.add_argument("--worlds", default="8")
    ap.add_argument("--collectives", default="allreduce,bcast,allgather")
    ap.add_argument("--sizes", default="65536,1048576,4194304")
    ap.add_argument("--shape", default="", choices=("", "ring", "logp"),
                    help="force the allreduce/allgather hop shape via "
                         "ACCL_RT_SHAPE in the child (crossover "
                         "calibration)")
    args = ap.parse_args()

    import os

    if args.shape:
        os.environ["ACCL_RT_SHAPE"] = args.shape

    rows = []
    for world in [int(w) for w in args.worlds.split(",")]:
        for name in args.collectives.split(","):
            for nbytes in [int(s) for s in args.sizes.split(",")]:
                row = run_config(name, nbytes, world, args.transport,
                                 args.iters)
                if row:
                    rows.append(row)
                    (n, b, w, t, it, s, passes, parks, park_ms, hit,
                     miss) = row
                    print(f"  {n:13s} {b:>9d}B w{w} {s*1e3:9.2f} ms/call"
                          f"  passes={passes} parks={parks}"
                          f" park_ms={park_ms:.1f} seek_hit={hit}"
                          f" seek_miss={miss}", file=sys.stderr)

    out = REPO / "accl_log" / args.out
    shape = args.shape or "auto"
    with open(out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["Collective", "Bytes", "World", "Transport", "Iters",
                    "SecondsPerCall", "Passes", "Parks", "ParkMs",
                    "SeekHit", "SeekMiss", "Shape"])
        w.writerows([(*r, shape) for r in rows])
    print(f"wrote {out} ({len(rows)} rows)")


if __name__ == "__main__":
    main()
