#!/usr/bin/env python
"""accl_synth: search the hop-DAG schedule space, certify winners, and
manage the committed synthesized-schedule library
(accl_tpu/sequencer/synthesized/, docs/synthesis.md).

Modes:

  --search            run the synthesize -> score -> prune -> certify
                      loop for every (op, world) in --ops/--worlds
                      (plus every --tiers factoring) and print the
                      winner table (no files written)
  --export            like --search, but write every winner to the
                      library directory and prune in-scope entries
                      that no longer win any cell (regenerates the
                      committed JSON hop-DAGs; diff should be empty
                      unless the generator or the scoring link
                      changed)
  --score             print the predicted synth-vs-hand-written time
                      per (world, size) cell for every committed entry
  --verify-library    re-certify every committed entry: the spec must
                      regenerate the committed DAG byte-for-byte, the
                      DAG must pass the semantic certifier + deep
                      model checker clean, and the committed win_bytes
                      window must match fresh scoring under the link
                      — TIERED entries re-score under the shipped
                      link_tiers per-tier calibration, never the flat
                      link (the CI gate that keeps a stale library,
                      stale selection window, or a checker change from
                      silently shipping an uncertified schedule)

  --tiers LxP [...]   factored topologies to search (e.g. 2x4 4x4):
                      each searches the tier-annotated hop-DAG space
                      over inner=L x outer=P, scored per tier against
                      the striped hand-written composition under the
                      shipped link_tiers calibration
  --beam N            certify only the N best predicted advantages per
                      (op, world) cell (branch-and-bound: losers are
                      pruned on the admissible alpha-beta bound BEFORE
                      any certification is paid; default: certify
                      every candidate with a non-empty window)

The scoring link defaults to the committed calibrated timing model
(accl_log/timing_model.json, bcast row — the same link ACCL.autotune
reads); --alpha-us/--beta-gbps override it. Tiered scoring reads the
same model's link_tiers section.

Exit status is 0 only when every requested gate holds.
"""

import argparse
import json
import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from accl_tpu.constants import Operation  # noqa: E402
from accl_tpu.sequencer import synthesis  # noqa: E402
from accl_tpu.sequencer.timing import LinkParams, emulator_link  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_MODEL = REPO / "accl_log" / "timing_model.json"


def _rel(path: pathlib.Path) -> pathlib.Path:
    """Repo-relative for display when possible (the library dir can be
    redirected outside the repo in tests)."""
    try:
        return path.relative_to(REPO)
    except ValueError:
        return path


OPS = {
    "allreduce": Operation.allreduce,
    "allgather": Operation.allgather,
    "reduce_scatter": Operation.reduce_scatter,
}


def load_link(args) -> LinkParams:
    if args.alpha_us is not None or args.beta_gbps is not None:
        if args.alpha_us is None or args.beta_gbps is None:
            raise SystemExit("pass both --alpha-us and --beta-gbps")
        return LinkParams(alpha=args.alpha_us * 1e-6,
                          beta=args.beta_gbps * 1e9)
    model = json.loads(pathlib.Path(args.timing_model).read_text())
    try:
        return emulator_link(model)
    except ValueError as e:
        raise SystemExit(f"{args.timing_model}: {e}") from e


def parse_tiers(specs: list[str]) -> list[tuple[int, int]]:
    out = []
    for s in specs:
        try:
            L, P = (int(x) for x in s.lower().split("x"))
        except ValueError:
            raise SystemExit(f"--tiers wants LxP (e.g. 2x4), got {s!r}")
        out.append((L, P))
    return out


def load_tier_links(args):
    from accl_tpu.telemetry.feedback import default_tier_links

    tiers = default_tier_links(args.timing_model)
    if tiers is None:
        raise SystemExit(
            f"{args.timing_model} carries no link_tiers (needed to "
            "score tiered candidates) — run bench.py --hier-gate")
    return tiers


def run_search(args, export: bool) -> bool:
    link = load_link(args)
    print(f"scoring link: alpha {link.alpha * 1e6:.2f} us, "
          f"beta {link.beta / 1e9:.3f} GB/s")
    n_winners = 0
    written: set[str] = set()
    grid = getattr(args, "grid", "std")
    if grid == "lat" and args.tiers:
        raise SystemExit("--grid lat scores flat candidates only "
                         "(tiered windows live behind the hier "
                         "register, not the latency window)")
    for world in args.worlds:
        for op_name in args.ops:
            results = synthesis.search(OPS[op_name], world, link,
                                       beam=args.beam, grid=grid,
                                       log=lambda m: print("  " + m))
            for res in results:
                n_winners += 1
                if export:
                    path = synthesis.export_entry(res)
                    written.add(path.name)
                    print(f"  wrote {_rel(path)}")
    tier_specs = parse_tiers(args.tiers or [])
    if tier_specs:
        tl = load_tier_links(args)
        print(f"tier links: inner alpha {tl.inner.alpha * 1e6:.1f} us "
              f"beta {tl.inner.beta / 1e9:.2f} GB/s / outer alpha "
              f"{tl.outer.alpha * 1e6:.1f} us beta "
              f"{tl.outer.beta / 1e9:.3f} GB/s")
        for L, P in tier_specs:
            results = synthesis.search(
                synthesis.Operation.allreduce, L * P, link,
                beam=args.beam, tiers=(L, P), tier_links=tl,
                log=lambda m: print("  " + m))
            for res in results:
                n_winners += 1
                if export:
                    path = synthesis.export_entry(res)
                    written.add(path.name)
                    print(f"  wrote {_rel(path)}")
    print(f"{n_winners} winner(s) across worlds {args.worlds} "
          f"x ops {args.ops} + tiers {args.tiers or []}")
    if export:
        # prune in-scope entries that stopped winning: after a timing-
        # or cost-model change an entry whose fresh window is None is
        # never rewritten by the loop above, and verify_library would
        # fail it forever with advice (--export) that otherwise could
        # not resolve the failure. Out-of-scope entries (ops/worlds/
        # factorings not searched this run) are kept untouched — a
        # flat search never prunes tiered entries and vice versa, and
        # a std-grid search never prunes latency-grid entries (nor the
        # reverse) — the two windows are scored on different grids.
        op_names = {OPS[o].name for o in args.ops}
        searched_tiers = set(tier_specs)
        for p in sorted(synthesis.library_dir().glob("*.json")):
            if p.name in written:
                continue
            spec = synthesis.SynthSpec.from_json(
                json.loads(p.read_text()))
            in_scope = (
                (spec.tiers and tuple(spec.tiers) in searched_tiers)
                or (not spec.tiers and spec.op in op_names
                    and spec.world in args.worlds
                    and spec.grid == grid))
            if in_scope:
                p.unlink()
                print(f"  pruned {_rel(p)} "
                      "(no longer wins any cell under this link)")
        synthesis.clear_library_cache()
    return n_winners > 0


def run_score(args) -> bool:
    link = load_link(args)
    entries = synthesis.library()
    if not entries:
        print("synthesized library is empty", file=sys.stderr)
        return False
    tl = None
    if any(e.spec.tiers for e in entries.values()):
        tl = load_tier_links(args)
    print(f"{'entry':44s} {'bytes':>10s} {'synth_us':>10s} "
          f"{'hand_us':>10s}  verdict")
    for key, entry in sorted(entries.items()):
        s = entry.spec
        for nbytes in synthesis.grid_for(s):
            count = max(nbytes // 4, 1)
            if s.tiers:
                # per-tier scoring against the striped composition —
                # the baseline a tiered entry actually displaces
                t_s = synthesis.predict_spec_tiered(tl, s, count, 4)
                t_h = synthesis.hand_written_tiered_best(
                    tl, count, 4, (s.tiers[0], s.tiers[1]))
            else:
                t_s = synthesis.predict_spec(link, s, count, 4)
                t_h = synthesis.hand_written_best(
                    link, s.scenario, count, 4, s.world, wire=s.wire)
            verdict = "WINS" if t_s < t_h else ("tie" if t_s == t_h
                                                else "loses")
            print(f"{key:44s} {nbytes:>10d} {t_s * 1e6:>10.1f} "
                  f"{t_h * 1e6:>10.1f}  {verdict}")
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--search", action="store_true",
                    help="run the search and print winners")
    ap.add_argument("--export", action="store_true",
                    help="run the search and (re)write the library")
    ap.add_argument("--score", action="store_true",
                    help="predicted synth-vs-hand-written per cell for "
                         "the committed library")
    ap.add_argument("--verify-library", action="store_true",
                    help="re-certify every committed entry (CI gate)")
    ap.add_argument("--worlds", type=int, nargs="+",
                    default=[2, 4, 8, 16])
    ap.add_argument("--ops", nargs="+", default=sorted(OPS),
                    choices=sorted(OPS))
    ap.add_argument("--tiers", nargs="+", default=None, metavar="LxP",
                    help="factored topologies to search, e.g. 2x4 4x4")
    ap.add_argument("--grid", default="std", choices=["std", "lat"],
                    help="scoring grid for flat searches: std = the "
                         "1 KiB-16 MiB bandwidth grid, lat = the "
                         "1-64 KiB latency grid behind "
                         "SYNTH_LATENCY_MAX_COUNT")
    ap.add_argument("--beam", type=int, default=None,
                    help="certify only the N best predicted advantages")
    ap.add_argument("--timing-model", default=str(DEFAULT_MODEL))
    ap.add_argument("--alpha-us", type=float, default=None)
    ap.add_argument("--beta-gbps", type=float, default=None)
    args = ap.parse_args(argv)
    if not (args.search or args.export or args.score
            or args.verify_library):
        ap.error("nothing to do: pass --search, --export, --score, or "
                 "--verify-library")
    ok = True
    if args.search or args.export:
        ok &= run_search(args, export=args.export)
    if args.score:
        ok &= run_score(args)
    if args.verify_library:
        from accl_tpu.telemetry.feedback import default_tier_links

        # tiered entries re-score under the SHIPPED per-tier
        # calibration of --timing-model (verify_library falls back to
        # the committed model's link_tiers when this resolves None)
        ok &= synthesis.verify_library(
            log=print, link=load_link(args),
            tier_links=default_tier_links(args.timing_model))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
