#!/usr/bin/env python3
"""Per-collective latency/throughput benchmark against the native emulator.

The Coyote benchmark app analog (reference test/host/Coyote/test.cpp:
per-collective latency/throughput logging with eager/rendezvous and
buffer-placement switches, results to accl_log/*.log): sweeps message
sizes across both protocols over N emulator ranks and writes
accl_log/emu_bench.csv — or emu_bench_udp.csv with --transport udp —
(Collective,Protocol,Bytes,Seconds,GBps,World).
"""

import argparse
import pathlib
import sys
import time

import numpy as np

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# THE eager/rx geometry of the emulator sweep, single-sourced in
# accl_tpu.telemetry.native: the in-file protocol labeler, the EmuWorld
# bring-up, the timing-model calibration (tools/timing_model.py), and
# the telemetry re-planning (span_cost / aggregate_wire_gbps) must all
# agree or rows near the eager/rendezvous boundary get mislabeled /
# misfitted silently.
from accl_tpu.telemetry.native import (  # noqa: E402
    DEFAULT_MAX_EAGER as MAX_EAGER,
    DEFAULT_RX_BUF as RX_BUF,
)
MAX_RNDZV = 64 * 1024 * 1024  # passed to EmuWorld AND the skip guard

# Calibration domain of the timing model (tools/timing_model.py):
# worlds past this stay in the CSVs as scale evidence but are excluded
# from alpha/beta fits — 32 threads on the single CI core enter a
# superlinear scheduling regime no linear link model spans.
FIT_MAX_WORLD = 16


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--world", type=int, default=4)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--transport", choices=("tcp", "udp", "local"),
                    default="tcp",
                    help="session TCP mesh, sessionless datagram POE, or "
                         "the intra-process direct-call POE")
    args = ap.parse_args()

    from accl_tpu import Operation, ReduceFunction, TuningParams
    from accl_tpu.device.emu_device import EmuWorld
    from accl_tpu.sequencer import Protocol, select_algorithm

    # the full per-collective sweep shape of the reference's bench.cpp
    # (every collective, 2^k element points); `nbytes` is the per-rank
    # payload of the named collective's natural unit
    COLLECTIVES = ("allreduce", "bcast", "allgather", "reduce", "scatter",
                   "gather", "reduce_scatter", "alltoall")

    def protocol_label(name: str, count: int) -> str:
        """Which protocol regime the row actually exercised, from the
        shared selection rules (plan.py) — NOT a size threshold: the
        datagram POE is eager-only, and allreduce rides the streamed
        eager ring/halving-doubling at every size by default."""
        if args.transport == "udp":
            return "eager"
        plan = select_algorithm(
            Operation[name], count, 4, args.world,
            max_eager_size=MAX_EAGER, eager_rx_buf_size=RX_BUF,
            tuning=TuningParams.default())
        return "rndzv" if plan.protocol == Protocol.RENDEZVOUS else "eager"

    w = EmuWorld(args.world, max_eager=MAX_EAGER, rx_buf_bytes=RX_BUF,
                 max_rndzv=MAX_RNDZV, transport=args.transport)
    rows = []
    try:
        # large worlds move gigabytes of aggregate wire bytes through
        # one CI core per 4 MB config; raise the housekeeping timeout
        # (the reference's runtime-configurable knob) so a slow sweep
        # point is measured, not killed
        from accl_tpu import CallOptions
        from accl_tpu.constants import CfgFunc, Operation as _Op

        def _cfg(rank, i):
            rank.call(CallOptions(scenario=_Op.config,
                                  function=int(CfgFunc.set_timeout),
                                  count=180_000))
        w.run(_cfg)
        for nbytes in (1024, 4096, 65536, 1 << 20, 4 << 20):
            count = nbytes // 4
            for name in COLLECTIVES:
                proto = protocol_label(name, count)
                # the rendezvous reduce_scatter composition reduces the
                # FULL world x count payload in one message; past the
                # configured max_rndzv ceiling (64 MB emulator default)
                # the runtime correctly refuses with DMA_SIZE_ERROR —
                # skip the config and say so (no silent caps)
                if (name == "reduce_scatter" and proto == "rndzv"
                        and nbytes * args.world > MAX_RNDZV):
                    print(f"{name:14s} {proto:6s} {nbytes:>9d} B "
                          f"SKIPPED (composition message "
                          f"{nbytes * args.world >> 20} MB > max_rndzv)",
                          file=sys.stderr)
                    continue

                def body(rank, i, _name=name, _n=count):
                    W = args.world
                    # only the named collective's operands, and wide
                    # buffers only where the rank's ROLE reads/writes
                    # them (a 4 MB point at w16 would otherwise
                    # allocate ~136 MB per rank for every collective)
                    wide_in = (_name in ("reduce_scatter", "alltoall")
                               or (_name == "scatter" and i == 0))
                    wide_out = (_name in ("alltoall", "allgather")
                                or (_name == "gather" and i == 0))
                    x = np.ones(_n * (W if wide_in else 1), np.float32)
                    out = np.zeros(_n * (W if wide_out else 1),
                                   np.float32)
                    rank.barrier()
                    t0 = time.perf_counter()
                    for _ in range(args.iters):
                        if _name == "allreduce":
                            rank.allreduce(x, out, _n, ReduceFunction.SUM)
                        elif _name == "bcast":
                            rank.bcast(x, _n, root=0)
                        elif _name == "allgather":
                            rank.allgather(x, out, _n)
                        elif _name == "reduce":
                            rank.reduce(x, out, _n, 0, ReduceFunction.SUM)
                        elif _name == "scatter":
                            rank.scatter(x, out, _n, 0)
                        elif _name == "gather":
                            rank.gather(x, out, _n, 0)
                        elif _name == "reduce_scatter":
                            rank.reduce_scatter(x, out, _n,
                                                ReduceFunction.SUM)
                        else:
                            rank.alltoall(x, out, _n)
                    return (time.perf_counter() - t0) / args.iters

                secs = max(w.run(body))
                gbps = nbytes / secs / 1e9
                rows.append((name, proto, nbytes, secs, gbps))
                print(f"{name:14s} {proto:6s} {nbytes:>9d} B "
                      f"{secs*1e6:10.1f} us  {gbps:7.3f} GB/s",
                      file=sys.stderr)
    finally:
        w.close()

    outdir = REPO / "accl_log"
    outdir.mkdir(exist_ok=True)
    csv = outdir / {"tcp": "emu_bench.csv", "udp": "emu_bench_udp.csv",
                    "local": "emu_bench_local.csv"}[args.transport]
    # merge by world: a run at one world size refreshes only its own rows,
    # so the committed artifact can accumulate a multi-world sweep
    kept = []
    if csv.exists():
        with open(csv) as f:
            header = f.readline()
            # only merge rows from the current 6-column format; an older
            # CSV (pre-World-column) is regenerated from scratch, else its
            # 5-field rows would survive every world filter and corrupt
            # the file
            if header.strip() == "Collective,Protocol,Bytes,Seconds,GBps,World":
                kept = [ln for ln in f
                        if ln.strip() and ln.rsplit(",", 1)[1].strip()
                        != str(args.world)]
    with open(csv, "w") as f:
        f.write("Collective,Protocol,Bytes,Seconds,GBps,World\n")
        f.writelines(kept)
        for r in rows:
            f.write(f"{r[0]},{r[1]},{r[2]},{r[3]:.6e},{r[4]:.3f},"
                    f"{args.world}\n")
    print(f"wrote {csv} ({len(rows)} new rows, {len(kept)} kept)")


if __name__ == "__main__":
    main()
