#!/usr/bin/env python3
"""native_check.py — static concurrency certifier for the native runtime.

The C++ twin of tools/accl_lint.py: a libclang pass over the three
native translation units behind the POE seam
(native/src/{runtime,transport,reliability}.cpp + headers) that emits
stable ACCLN1xx diagnostics. The Python linter certifies descriptor
batches before dispatch; this tool certifies the layer those proofs
stand on — the threaded C++ runtime itself — at commit time instead of
debug time (the two worst native bugs to date, PR 14's rx-thread
blocking retransmit and PR 13's reconfiguration fence, were both
concurrency hazards found by review/fuzz, not tooling).

Rules (docs/lint.md has the full table + worked examples):

  ACCLN100  infrastructure: a TU failed to parse (never silently skipped)
  ACCLN101  rx no-blocking: a function that can block UNBOUNDED on a
            peer (send_all / writev_all flush loops, unbounded
            condition_variable::wait, poll(-1), kernel connect/accept)
            is reachable from an rx-thread role. Bounded waits
            (wait_for / wait_until / poll with a finite timeout) and
            kernel-bounded datagram sends are allowed — the rule is
            about PEER-bounded blocking, the PR 14 mutual-wedge class.
  ACCLN102  lock-order acyclicity: the global lock graph (intra-
            procedural lock_guard/unique_lock nesting + locks acquired
            transitively through calls made while holding) must be
            acyclic. The witness cycle is rendered in the diagnostic.
            Self-edges (re-acquiring a held std::mutex) are cycles too.
  ACCLN103  guarded fields: every non-atomic, non-const shared field of
            the audited structs (accl_rt, TcpPoe, UdpPoe, LocalPoe)
            must carry an annotation, and every access must honor it:
              // ACCL_GUARDED_BY(mu)    access only while holding mu
              // ACCL_INIT_CONST        written only during init roles
              // ACCL_ROLE_ONLY(role)   accessed only by that role
            Functions may declare // ACCL_REQUIRES(mu): callers must
            hold mu (checked) and the body analyzes as holding it.
  ACCLN104  seam rules: the shell-grep seamcheck absorbed as data —
            transport.cpp must not include reliability.h nor reference
            session-side reliability symbols (the POE seam carries
            already-built frames only).
  ACCLN105  rx prints: no fprintf/std::cerr reachable from an rx-thread
            role outside an if gated on the cached debug flag (a chaos
            soak must never turn the rx loop into a logging loop).

Thread roles are inferred from the real roots, never declared:
  - lambdas handed to std::thread, classified by the member/variable
    that owns the thread (rx_threads_/rx_thread_ -> rx, seq_thread ->
    seq, rely_thread -> rely, fault_threads -> fault, a local
    `std::thread acceptor(..)` -> acceptor)
  - public accl_rt_* entry points (create* -> init, destroy -> fini,
    everything else -> api)
and propagated over the call graph. Propagation is ENGINE-AWARE: a
role that enters a Poe engine class (TcpPoe/UdpPoe/LocalPoe) carries
that engine tag, and virtual Poe calls resolve only to the tagged
engine's overrides — one runtime holds exactly one engine, so an rx
role rooted in UdpPoe can never reach TcpPoe::send_frames. Functions
may restrict which engines' roles enter them with // ACCL_POE(udp,local)
(e.g. the mem-backed landing path the stream POE never calls).

Site-level waivers: // ACCL_ALLOW(ACCLN101: reason) on the flagged
line suppresses that diagnostic and is REPORTED in --tree output — a
waiver is a visible, auditable claim, never a silent hole.

Usage:
  native_check.py --tree           certify the live native sources
  native_check.py --corpus [DIR]   replay the fixture corpus (default
                                   tools/native_lint_corpus/): every
                                   fixture's diagnosed code set must
                                   EXACTLY equal its // EXPECT set
  native_check.py --seam           ACCLN104 only (the `make -C native
                                   seamcheck` wrapper; no libclang)

Exit status 0 only when every expectation holds — the CI lint job runs
`native_check.py --corpus --tree` as a gate.
"""

from __future__ import annotations

import argparse
import pathlib
import re
import subprocess
import sys
from dataclasses import dataclass, field

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
DEFAULT_CORPUS = pathlib.Path(__file__).resolve().parent / "native_lint_corpus"
TREE_TUS = [
    NATIVE / "src" / "runtime.cpp",
    NATIVE / "src" / "transport.cpp",
    NATIVE / "src" / "reliability.cpp",
]

# ---------------------------------------------------------------------------
# Rule data (the tool's "registers": every list here is policy, not code)
# ---------------------------------------------------------------------------

# thread-owning member -> role of the lambda handed to it
THREAD_MEMBER_ROLES = {
    "rx_threads_": "rx",
    "rx_thread_": "rx",
    "seq_thread": "seq",
    "rely_thread": "rely",
    "fault_threads": "fault",
}
# local std::thread variables keep their own name as the role
# (the TCP acceptor); anything unrecognized becomes role "thread"

# Poe engine classes: a role entering one carries its tag, and virtual
# Poe calls resolve only to the tagged engine (one runtime, one engine)
ENGINE_TAGS = {"TcpPoe": "tcp", "UdpPoe": "udp", "LocalPoe": "local"}

# in-tree flush loops that block until the PEER drains (ACCLN101)
BLOCKING_FREE_FNS = {"send_all", "writev_all"}
# kernel calls that block on a peer (poll handled separately: only the
# infinite -1 timeout is peer-bounded)
BLOCKING_SYS_FNS = {"connect", "accept"}
# roles forbidden to reach blocking sites (the rx loops must always
# drain their sockets; seq/rely/fault/api are senders and may block)
NO_BLOCK_ROLES = {"rx"}
# single-threaded phases: accesses there need no locks (threads either
# don't exist yet or are already joined)
INIT_ROLES = {"init"}
FINI_ROLES = {"fini"}

# structs whose every shared field must be annotated (ACCLN103); corpus
# fixtures extend this with // ACCL_AUDITED class markers
AUDITED_CLASSES = {"accl_rt", "TcpPoe", "UdpPoe", "LocalPoe"}
# field types that are their own synchronization (or the primitives);
# PoeStats is the transport's all-atomic counter block (transport.h)
EXEMPT_TYPE_RE = re.compile(
    r"atomic|mutex|condition_variable|\bthread\b|std::thread|\bPoeStats\b")
# container methods that mutate (write-classification for ROLE_ONLY /
# INIT_CONST fields of container type)
MUTATING_METHODS = {
    "push_back", "emplace_back", "pop_back", "pop_front", "push_front",
    "clear", "resize", "erase", "insert", "emplace", "assign", "reserve",
}

# ACCLN104: the seamcheck grep, as data. `file` matches the basename.
SEAM_RULES = [
    {
        "file": "transport.cpp",
        "forbid_include": r'#\s*include\s*"reliability',
        "reason": "the POE seam carries already-built frames: transport "
                  "must not include reliability internals",
    },
    {
        "file": "transport.cpp",
        "forbid_symbols": ["crc32c", "frame_crc", "RetxBuf", "RetxFrame",
                           "HeldFrame", "WantState"],
        "reason": "CRC and retransmit retention are session-side policy "
                  "above the seam",
    },
]

ANNOT_RE = re.compile(
    r"ACCL_(GUARDED_BY|REQUIRES|INIT_CONST|ROLE_ONLY|POE|ALLOW|AUDITED)"
    r"(?:\(([^)]*)\))?")
EXPECT_RE = re.compile(r"//\s*EXPECT:\s*([A-Z0-9,\s]+)")
AS_FILE_RE = re.compile(r"//\s*AS_FILE:\s*(\S+)")


# ---------------------------------------------------------------------------
# libclang bring-up
# ---------------------------------------------------------------------------

def _gcc_include_dirs() -> list[str]:
    """System C++ include paths from the host g++ (libclang's pip wheel
    ships no builtin headers, so we hand it gcc's search list)."""
    try:
        out = subprocess.run(
            ["g++", "-E", "-v", "-x", "c++", "-"], input="",
            capture_output=True, text=True, timeout=30).stderr
    except (OSError, subprocess.TimeoutExpired):
        return []
    dirs, active = [], False
    for ln in out.splitlines():
        if ln.startswith("#include <...>"):
            active = True
        elif ln.startswith("End of search"):
            active = False
        elif active and ln.startswith(" "):
            dirs.append(ln.strip())
    return dirs


def clang_args(extra_includes: list[str] | None = None) -> list[str]:
    args = ["-x", "c++", "-std=c++17", "-nostdinc", "-nostdinc++"]
    for d in _gcc_include_dirs():
        args += ["-I", d]
    for d in extra_includes or []:
        args += ["-I", d]
    return args


def load_cindex():
    try:
        from clang import cindex
        cindex.Index.create()
        return cindex
    except Exception as e:  # pragma: no cover - environment-specific
        print(f"native_check: libclang unavailable ({e})", file=sys.stderr)
        return None


# ---------------------------------------------------------------------------
# Source annotations (trailing comments on the declaration line or the
# line above; read straight from the file, not the AST)
# ---------------------------------------------------------------------------

@dataclass
class FileAnnotations:
    # line -> list of (kind, arg)
    by_line: dict[int, list[tuple[str, str]]] = field(default_factory=dict)

    def at(self, line: int, kind: str) -> str | None:
        """Annotation of `kind` on `line` or the line above; the arg
        (possibly empty) or None."""
        for ln in (line, line - 1):
            for k, a in self.by_line.get(ln, []):
                if k == kind:
                    return a
        return None

    def field_annotation(self, line: int) -> tuple[str, str] | None:
        """First field annotation on `line`, else on the line above —
        the decl line always wins, so adjacent fields with different
        guards never capture each other's annotation."""
        for ln in (line, line - 1):
            for k, a in self.by_line.get(ln, []):
                if k in ("GUARDED_BY", "INIT_CONST", "ROLE_ONLY"):
                    return (k, a)
        return None

    def allow(self, line: int, code: str) -> str | None:
        """ACCL_ALLOW(<code>: reason) waiver covering `line`."""
        for ln in (line, line - 1):
            for k, a in self.by_line.get(ln, []):
                if k == "ALLOW" and a.split(":", 1)[0].strip() == code:
                    return (a.split(":", 1)[1].strip()
                            if ":" in a else "(no reason)")
        return None


def read_annotations(path: pathlib.Path) -> FileAnnotations:
    fa = FileAnnotations()
    try:
        text = path.read_text()
    except OSError:
        return fa
    for i, ln in enumerate(text.splitlines(), start=1):
        if "ACCL_" not in ln:
            continue
        comment = ln.split("//", 1)
        if len(comment) < 2:
            continue
        for m in ANNOT_RE.finditer(comment[1]):
            fa.by_line.setdefault(i, []).append(
                (m.group(1), (m.group(2) or "").strip()))
    return fa


# ---------------------------------------------------------------------------
# Model: what one pass over the ASTs extracts
# ---------------------------------------------------------------------------

@dataclass
class LockEvent:
    mutex: str          # canonical: Class::member or bare global name
    offset: int
    line: int
    file: str
    held: tuple[str, ...]  # mutexes already held at this acquisition


@dataclass
class CallSite:
    targets: tuple[str, ...]  # candidate callee USRs (virtual -> many)
    name: str
    line: int
    file: str
    offset: int
    held: tuple[str, ...]


@dataclass
class BlockSite:
    what: str           # human description of the blocking primitive
    line: int
    file: str


@dataclass
class PrintSite:
    what: str
    line: int
    file: str
    debug_gated: bool


@dataclass
class FieldAccess:
    cls: str
    fld: str
    line: int
    file: str
    held: tuple[str, ...]
    write: bool


@dataclass
class FuncInfo:
    usr: str
    name: str            # display name (Class::method or lambda@file:line)
    cls: str | None      # enclosing class name ('' for free functions)
    file: str
    line: int
    requires: tuple[str, ...] = ()
    poe_only: tuple[str, ...] = ()   # ACCL_POE engine restriction
    calls: list[CallSite] = field(default_factory=list)
    locks: list[LockEvent] = field(default_factory=list)
    blocking: list[BlockSite] = field(default_factory=list)
    prints: list[PrintSite] = field(default_factory=list)
    accesses: list[FieldAccess] = field(default_factory=list)


@dataclass
class FieldInfo:
    cls: str
    name: str
    type_spelling: str
    file: str
    line: int
    annotation: tuple[str, str] | None   # (kind, arg)
    exempt: bool


@dataclass
class ThreadRoot:
    usr: str             # the lambda's synthetic USR
    role: str
    engine: str | None
    file: str
    line: int


@dataclass
class Model:
    funcs: dict[str, FuncInfo] = field(default_factory=dict)
    fields: dict[tuple[str, str], FieldInfo] = field(default_factory=dict)
    roots: list[ThreadRoot] = field(default_factory=list)
    # virtual base method USR -> override USRs (by name across hierarchy)
    overrides: dict[str, list[str]] = field(default_factory=dict)
    cls_of_usr: dict[str, str] = field(default_factory=dict)
    audited: set[str] = field(default_factory=set)
    annotations: dict[str, FileAnnotations] = field(default_factory=dict)
    parse_errors: list[str] = field(default_factory=list)


@dataclass
class Diag:
    code: str
    file: str
    line: int
    message: str
    detail: list[str] = field(default_factory=list)

    def render(self) -> str:
        head = f"{self.code} {self.file}:{self.line}: {self.message}"
        return "\n".join([head] + [f"    {d}" for d in self.detail])


# ---------------------------------------------------------------------------
# AST extraction
# ---------------------------------------------------------------------------

class Extractor:
    """One walk per TU. Tracks, along the (source-ordered) preorder
    walk: active lock guards with linear unlock()/lock() toggling,
    enclosing-if debug gating, assignment-LHS write context, and lambda
    boundaries (a lambda body is its own function; guards never leak
    across — the body runs later, under the callee's locks)."""

    def __init__(self, cindex, model: Model, tree_files: set[str]):
        self.ci = cindex
        self.K = cindex.CursorKind
        self.model = model
        self.tree_files = tree_files

    # -- helpers ------------------------------------------------------------

    def _file_of(self, cursor) -> str | None:
        f = cursor.location.file
        return f.name if f else None

    def _in_tree(self, cursor) -> bool:
        f = self._file_of(cursor)
        return f is not None and f in self.tree_files

    def _annot(self, cursor) -> FileAnnotations:
        f = self._file_of(cursor)
        return self.model.annotations.setdefault(
            f, read_annotations(pathlib.Path(f))) if f else FileAnnotations()

    def _tokens(self, cursor) -> list[str]:
        try:
            return [t.spelling for t in cursor.get_tokens()]
        except Exception:
            return []

    def _mutex_name(self, cursor) -> str | None:
        """Canonical name of the mutex expression inside a guard ctor:
        Class::member for member mutexes (an indexed tx_mu_[i] vector
        collapses onto one node — every element orders identically),
        the bare spelling for globals/locals."""
        K = self.K
        for c in cursor.walk_preorder():
            if c.kind == K.MEMBER_REF_EXPR and c.referenced is not None:
                par = c.referenced.semantic_parent
                cls = par.spelling if par is not None else ""
                return f"{cls}::{c.spelling}" if cls else c.spelling
            if c.kind == K.DECL_REF_EXPR and c.referenced is not None:
                if "mutex" in (c.referenced.type.spelling or ""):
                    return c.spelling
        return None

    def _callee(self, call):
        try:
            return call.referenced
        except Exception:
            return None

    def _expand_virtual(self, ref) -> tuple[str, ...]:
        usr = ref.get_usr()
        targets = [usr]
        targets += self.model.overrides.get(usr, [])
        return tuple(dict.fromkeys(targets))

    # -- pass 1: classes, fields, hierarchy, overrides ----------------------

    def scan_classes(self, tu_cursor):
        K = self.K
        bases: dict[str, list[str]] = {}
        methods: dict[str, list] = {}  # class -> method cursors

        def scan(c):
            if c.kind in (K.STRUCT_DECL, K.CLASS_DECL) and c.is_definition():
                if self._in_tree(c):
                    self._scan_class(c, bases, methods)
            for ch in c.get_children():
                if ch.kind in (K.NAMESPACE, K.STRUCT_DECL, K.CLASS_DECL,
                               K.UNEXPOSED_DECL, K.LINKAGE_SPEC):
                    scan(ch)
        scan(tu_cursor)

        # name-based override resolution (this binding exposes no
        # get_overridden_cursors): derived method overrides any virtual
        # same-named method of a transitive base
        def all_bases(cls, seen=None):
            seen = seen or set()
            for b in bases.get(cls, []):
                if b not in seen:
                    seen.add(b)
                    all_bases(b, seen)
            return seen

        virt: dict[tuple[str, str], str] = {}
        for cls, ms in methods.items():
            for m in ms:
                if m.is_virtual_method():
                    virt[(cls, m.spelling)] = m.get_usr()
        for cls, ms in methods.items():
            for m in ms:
                for b in all_bases(cls):
                    busr = virt.get((b, m.spelling))
                    if busr and busr != m.get_usr():
                        self.model.overrides.setdefault(busr, []).append(
                            m.get_usr())

    def _scan_class(self, c, bases, methods):
        K = self.K
        cls = c.spelling
        fa = self._annot(c)
        if fa.at(c.location.line, "AUDITED") is not None:
            self.model.audited.add(cls)
        for ch in c.get_children():
            if ch.kind == K.CXX_BASE_SPECIFIER:
                for t in ch.get_children():
                    if t.kind == K.TYPE_REF and t.referenced is not None:
                        bases.setdefault(cls, []).append(
                            t.referenced.spelling)
            elif ch.kind in (K.CXX_METHOD, K.CONSTRUCTOR, K.DESTRUCTOR):
                methods.setdefault(cls, []).append(ch)
                self.model.cls_of_usr[ch.get_usr()] = cls
            elif ch.kind == K.FIELD_DECL:
                ty = ch.type.spelling or ""
                fann = fa.field_annotation(ch.location.line)
                exempt = (bool(EXEMPT_TYPE_RE.search(ty))
                          or ty.startswith("const ")
                          or ch.type.is_const_qualified())
                key = (cls, ch.spelling)
                if key not in self.model.fields:
                    self.model.fields[key] = FieldInfo(
                        cls, ch.spelling, ty, self._file_of(ch) or "?",
                        ch.location.line, fann, exempt)
            elif ch.kind in (K.STRUCT_DECL, K.CLASS_DECL) and \
                    ch.is_definition():
                self._scan_class(ch, bases, methods)

    # -- pass 2: function bodies -------------------------------------------

    def scan_functions(self, tu_cursor):
        K = self.K

        def scan(c):
            if c.kind in (K.FUNCTION_DECL, K.CXX_METHOD, K.CONSTRUCTOR,
                          K.DESTRUCTOR) and c.is_definition():
                if self._in_tree(c):
                    self._scan_function(c)
                return
            for ch in c.get_children():
                scan(ch)
        scan(tu_cursor)

    def _func_display(self, c) -> str:
        par = c.semantic_parent
        cls = par.spelling if par is not None and par.kind in (
            self.K.STRUCT_DECL, self.K.CLASS_DECL) else None
        return (f"{cls}::{c.spelling}" if cls else c.spelling), (cls or None)

    def _scan_function(self, c, usr=None, name=None, cls=None):
        if usr is None:
            usr = c.get_usr()
            name, cls = self._func_display(c)
        if usr in self.model.funcs:
            return
        fa = self._annot(c)
        req = fa.at(c.location.line, "REQUIRES")
        poe = fa.at(c.location.line, "POE")
        fi = FuncInfo(
            usr=usr, name=name, cls=cls,
            file=self._file_of(c) or "?", line=c.location.line,
            requires=tuple(s.strip() for s in req.split(",")) if req else (),
            poe_only=tuple(s.strip() for s in poe.split(",")) if poe else ())
        self.model.funcs[usr] = fi
        body = None
        for ch in c.get_children():
            if ch.kind == self.K.COMPOUND_STMT:
                body = ch
        if body is not None:
            st = _WalkState(fi, self)
            st.walk(body)


class _WalkState:
    """Per-function-body walk state (also used for each lambda body,
    which gets its own FuncInfo and a fresh guard stack)."""

    def __init__(self, fi: FuncInfo, ex: Extractor):
        self.fi = fi
        self.ex = ex
        self.K = ex.K
        self.guards: list[dict] = []   # {mutex, var, scope_end, released}
        self.compounds: list[int] = []  # extent.end offsets
        self.if_conds: list[str] = []
        self.write_depth = 0
        self.stack: list = []          # ancestor cursors (spawn detection)

    # ---- held-set bookkeeping

    def held(self, offset: int) -> tuple[str, ...]:
        out = list(self.fi.requires)
        for g in self.guards:
            if g["offset"] <= offset <= g["scope_end"] and not g["released"]:
                if g["mutex"] not in out:
                    out.append(g["mutex"])
        return tuple(out)

    # ---- main dispatch

    def walk(self, node):
        self.stack.append(node)
        try:
            self._walk(node)
        finally:
            self.stack.pop()

    def _walk(self, node):
        K = self.K
        kind = node.kind
        if kind == K.LAMBDA_EXPR:
            self._handle_lambda(node)
            return
        if kind == K.COMPOUND_STMT:
            self.compounds.append(node.extent.end.offset)
            for ch in node.get_children():
                self.walk(ch)
            self.compounds.pop()
            return
        if kind == K.IF_STMT:
            self._handle_if(node)
            return
        if kind == K.VAR_DECL:
            self._maybe_guard(node)
            for ch in node.get_children():
                self.walk(ch)
            return
        if kind == K.CALL_EXPR:
            self._handle_call(node)
            # children still carry member refs / nested calls
            for ch in node.get_children():
                self.walk(ch)
            return
        if kind == K.BINARY_OPERATOR:
            self._handle_binop(node)
            return
        if kind == K.UNARY_OPERATOR:
            self._handle_unop(node)
            return
        if kind == K.MEMBER_REF_EXPR:
            self._record_member(node)
            for ch in node.get_children():
                self.walk(ch)
            return
        if kind == K.DECL_REF_EXPR:
            self._maybe_cerr(node)
            return
        for ch in node.get_children():
            self.walk(ch)

    # ---- constructs

    def _handle_if(self, node):
        children = list(node.get_children())
        if not children:
            return
        cond, rest = children[0], children[1:]
        self.walk(cond)
        cond_text = " ".join(self.ex._tokens(cond))
        self.if_conds.append(cond_text)
        for ch in rest:
            self.walk(ch)
        self.if_conds.pop()

    def _maybe_guard(self, node):
        ty = node.type.spelling or ""
        if not any(t in ty for t in ("lock_guard", "unique_lock",
                                     "scoped_lock")):
            return
        mu = self.ex._mutex_name(node)
        if mu is None:
            return
        offset = node.location.offset
        scope_end = self.compounds[-1] if self.compounds else 1 << 60
        held_now = self.held(offset)
        self.guards.append(dict(mutex=mu, var=node.spelling, offset=offset,
                                scope_end=scope_end, released=False))
        self.fi.locks.append(LockEvent(
            mutex=mu, offset=offset, line=node.location.line,
            file=self.ex._file_of(node) or "?", held=held_now))

    def _handle_call(self, node):
        K = self.K
        name = node.spelling or ""
        line = node.location.line
        offset = node.location.offset
        file = self.ex._file_of(node) or "?"
        held = self.held(offset)

        # unique_lock unlock()/lock() toggles on a tracked guard var
        if name in ("unlock", "lock"):
            base = self._call_base_name(node)
            for g in self.guards:
                if g["var"] and g["var"] == base:
                    if name == "unlock":
                        g["released"] = True
                    else:
                        g["released"] = False
                        g["offset"] = min(g["offset"], offset)
                        self.fi.locks.append(LockEvent(
                            mutex=g["mutex"], offset=offset, line=line,
                            file=file,
                            held=tuple(m for m in held
                                       if m != g["mutex"])))
                    return

        # bare mutex .lock()/.unlock() (rare; treated like a guard-less
        # acquisition for ordering purposes only)
        ref = self.ex._callee(node)

        # condition_variable wait: unbounded -> blocking
        if name in ("wait", "wait_for", "wait_until"):
            base_ty = self._call_base_type(node)
            if base_ty and "condition_variable" in base_ty:
                if name == "wait":
                    self.fi.blocking.append(BlockSite(
                        "unbounded condition_variable::wait", line, file))
                return  # cv waits are not call-graph edges we care about

        # poll with a literal -1 timeout
        if name == "poll" and self._poll_is_infinite(node):
            self.fi.blocking.append(BlockSite(
                "poll with infinite (-1) timeout", line, file))

        # fprintf / printf
        if name in ("fprintf", "printf"):
            self.fi.prints.append(PrintSite(
                name, line, file, self._debug_gated()))

        if ref is not None:
            rname = ref.spelling or name
            rfile = self.ex._file_of(ref)
            in_tree = rfile in self.ex.tree_files if rfile else False
            if rname in BLOCKING_FREE_FNS and \
                    ref.kind == K.FUNCTION_DECL:
                self.fi.blocking.append(BlockSite(
                    f"{rname} flush loop (blocks until the peer drains)",
                    line, file))
            elif rname in BLOCKING_SYS_FNS and \
                    ref.kind == K.FUNCTION_DECL and not in_tree:
                self.fi.blocking.append(BlockSite(
                    f"kernel {rname}() (peer-bounded)", line, file))
            if ref.kind in (K.FUNCTION_DECL, K.CXX_METHOD, K.CONSTRUCTOR,
                            K.DESTRUCTOR):
                targets = (self.ex._expand_virtual(ref)
                           if ref.kind == K.CXX_METHOD and
                           ref.is_virtual_method()
                           else (ref.get_usr(),))
                self.fi.calls.append(CallSite(
                    targets=targets, name=rname, line=line, file=file,
                    offset=offset, held=held))
            # mutating container method on an audited field -> write
            if name in MUTATING_METHODS:
                self._record_member_base(node, write=True)

    def _handle_binop(self, node):
        children = list(node.get_children())
        op = self._binop_op(node, children)
        if op and (op == "=" or (op.endswith("=") and
                                 op not in ("==", "!=", "<=", ">="))):
            if children:
                self.write_depth += 1
                self.walk(children[0])
                self.write_depth -= 1
                for ch in children[1:]:
                    self.walk(ch)
                return
        for ch in children:
            self.walk(ch)

    def _handle_unop(self, node):
        toks = self.ex._tokens(node)
        if toks and (toks[0] in ("++", "--") or toks[-1] in ("++", "--")):
            self.write_depth += 1
            for ch in node.get_children():
                self.walk(ch)
            self.write_depth -= 1
            return
        for ch in node.get_children():
            self.walk(ch)

    def _handle_lambda(self, node):
        """A lambda is its own function. If it's handed to std::thread
        (detected from the ancestor chain: a thread-typed local, or a
        thread ctor / container-emplace / assignment whose target member
        is one of the configured thread owners), it becomes a thread
        ROOT with a FRESH lock context; otherwise it's approximated as
        called where defined (cv.wait predicates, comparators,
        std::function callbacks) and INHERITS the definition site's
        held locks as its requires set — a cv.wait predicate runs under
        the waited lock, and that is where the sequencer touches its
        queues."""
        loc = node.location
        usr = f"lambda@{self.ex._file_of(node)}:{loc.line}:{loc.column}"
        role = self._thread_role(node)
        fi = FuncInfo(usr=usr, name=f"{self.fi.name}::lambda@{loc.line}",
                      cls=self.fi.cls, file=self.ex._file_of(node) or "?",
                      line=loc.line)
        self.ex.model.funcs[usr] = fi
        if role is not None:
            engine = ENGINE_TAGS.get(self.fi.cls or "")
            self.ex.model.roots.append(ThreadRoot(
                usr=usr, role=role, engine=engine,
                file=fi.file, line=loc.line))
        else:
            held_now = self.held(loc.offset)
            # an explicit // ACCL_REQUIRES(mu) on the lambda overrides
            # the inherit-at-definition default (for helpers defined
            # unlocked but only ever invoked under the lock)
            fa = self.ex._annot(node)
            req = fa.at(loc.line, "REQUIRES")
            fi.requires = (tuple(s.strip() for s in req.split(","))
                           if req else held_now)
            self.fi.calls.append(CallSite(
                targets=(usr,), name=fi.name, line=loc.line, file=fi.file,
                offset=loc.offset, held=held_now))
        sub = _WalkState(fi, self.ex)
        for ch in node.get_children():
            if ch.kind == self.K.COMPOUND_STMT:
                sub.walk(ch)

    def _thread_role(self, lam) -> str | None:
        K = self.K
        saw_thread_ctor = False
        for node in reversed(self.stack[:-1]):
            k = node.kind
            if k == K.COMPOUND_STMT:
                break  # reached statement level: not a spawn argument
            if k == K.VAR_DECL and "thread" in (node.type.spelling or ""):
                return node.spelling or "thread"
            if k in (K.CALL_EXPR, K.CXX_FUNCTIONAL_CAST_EXPR):
                nm = node.spelling or ""
                if nm in ("emplace_back", "push_back", "operator=",
                          "thread"):
                    member = self._owner_member(node, lam)
                    if member in THREAD_MEMBER_ROLES:
                        return THREAD_MEMBER_ROLES[member]
                    if nm == "thread":
                        saw_thread_ctor = True
                        continue  # operator= / var decl may wrap the ctor
                    if nm in ("emplace_back", "push_back") and \
                            member is not None:
                        return None  # emplace on a non-thread container
        return "thread" if saw_thread_ctor else None

    def _owner_member(self, node, lam) -> str | None:
        """First member/var referenced by `node`'s subtree OUTSIDE the
        lambda itself — the container or member the thread lands in."""
        K = self.K
        lam_start = lam.extent.start.offset
        lam_end = lam.extent.end.offset
        for c in node.walk_preorder():
            off = c.location.offset
            if lam_start <= off <= lam_end:
                continue
            if c.kind in (K.MEMBER_REF_EXPR, K.DECL_REF_EXPR) and \
                    c.spelling and c.spelling in THREAD_MEMBER_ROLES:
                return c.spelling
        # fall back: first non-method member ref outside the lambda
        for c in node.walk_preorder():
            off = c.location.offset
            if lam_start <= off <= lam_end:
                continue
            if c.kind == K.MEMBER_REF_EXPR and c.referenced is not None \
                    and c.referenced.kind == K.FIELD_DECL:
                return c.spelling
        return None

    # ---- member refs / writes

    def _record_member(self, node):
        ref = node.referenced
        if ref is None or ref.kind != self.K.FIELD_DECL:
            return
        par = ref.semantic_parent
        cls = par.spelling if par is not None else ""
        if cls not in self.ex.model.audited:
            return
        offset = node.location.offset
        self.fi.accesses.append(FieldAccess(
            cls=cls, fld=node.spelling, line=node.location.line,
            file=self.ex._file_of(node) or "?", held=self.held(offset),
            write=self.write_depth > 0))

    def _record_member_base(self, call_node, write: bool):
        """`field.push_back(..)`: the field member ref under the method
        member ref is a WRITE access (the plain walk also records it as
        a read; the write record is the stricter one and both are
        checked)."""
        K = self.K
        for ch in call_node.get_children():
            if ch.kind == K.MEMBER_REF_EXPR:
                for base in ch.get_children():
                    if base.kind == K.MEMBER_REF_EXPR and \
                            base.referenced is not None and \
                            base.referenced.kind == K.FIELD_DECL:
                        par = base.referenced.semantic_parent
                        cls = par.spelling if par is not None else ""
                        if cls in self.ex.model.audited:
                            self.fi.accesses.append(FieldAccess(
                                cls=cls, fld=base.spelling,
                                line=base.location.line,
                                file=self.ex._file_of(base) or "?",
                                held=self.held(base.location.offset),
                                write=True))
                break

    def _maybe_cerr(self, node):
        if node.spelling == "cerr":
            self.fi.prints.append(PrintSite(
                "std::cerr", node.location.line,
                self.ex._file_of(node) or "?", self._debug_gated()))

    # ---- small probes

    def _debug_gated(self) -> bool:
        return any(re.search(r"debug", c) for c in self.if_conds)

    def _call_base_name(self, call) -> str | None:
        K = self.K
        for ch in call.get_children():
            if ch.kind == K.MEMBER_REF_EXPR:
                for b in ch.get_children():
                    if b.kind in (K.DECL_REF_EXPR, K.MEMBER_REF_EXPR):
                        return b.spelling
        return None

    def _call_base_type(self, call) -> str | None:
        K = self.K
        for ch in call.get_children():
            if ch.kind == K.MEMBER_REF_EXPR:
                for b in ch.get_children():
                    if b.kind in (K.DECL_REF_EXPR, K.MEMBER_REF_EXPR):
                        try:
                            return b.type.spelling
                        except Exception:
                            return None
        return None

    def _poll_is_infinite(self, call) -> bool:
        args = list(call.get_arguments())
        if len(args) >= 3:
            toks = "".join(self.ex._tokens(args[2]))
            return toks == "-1"
        return False

    def _binop_op(self, node, children) -> str | None:
        """Operator token of a BINARY_OPERATOR: the first token after
        the first child's extent (this binding has no .binary_operator)."""
        if not children:
            return None
        end = children[0].extent.end.offset
        for t in node.get_tokens():
            if t.extent.start.offset >= end:
                return t.spelling
        return None


# ---------------------------------------------------------------------------
# Model construction
# ---------------------------------------------------------------------------

def build_model(cindex, tus: list[pathlib.Path],
                include_dirs: list[str]) -> Model:
    model = Model()
    model.audited = set(AUDITED_CLASSES)
    idx = cindex.Index.create()
    args = clang_args(include_dirs)
    resolved_cache: dict[str, str] = {}

    def resolve(name: str) -> str:
        if name not in resolved_cache:
            resolved_cache[name] = str(pathlib.Path(name).resolve())
        return resolved_cache[name]

    # every file under native/ (or the fixture itself) is "in tree":
    # its definitions enter the model; system headers never do
    tree_prefixes = [str(NATIVE.resolve())] + \
        [str(p.resolve()) for p in tus]

    parsed = []
    for tu_path in tus:
        tu = idx.parse(str(tu_path.resolve()), args=args)
        fatal = [str(d) for d in tu.diagnostics if d.severity >= 3]
        if fatal:
            model.parse_errors.append(
                f"{tu_path}: {fatal[0]}")
            continue
        parsed.append(tu)

    class _Ex(Extractor):
        def _file_of(self, cursor):
            f = cursor.location.file
            return resolve(f.name) if f else None

        def _in_tree(self, cursor):
            f = self._file_of(cursor)
            return f is not None and any(
                f.startswith(p) for p in tree_prefixes)

    for tu in parsed:
        ex = _Ex(cindex, model, set())
        ex.scan_classes(tu.cursor)
    # AUDITED markers discovered in pass 1 must be visible in pass 2
    for tu in parsed:
        ex = _Ex(cindex, model, set())
        ex.scan_functions(tu.cursor)
    return model


# ---------------------------------------------------------------------------
# Role propagation (engine-aware)
# ---------------------------------------------------------------------------

def propagate_roles(model: Model):
    """BFS of (function, role, engine) states from the thread roots and
    the C entry points. Returns (roles, parents): roles[usr] = set of
    (role, engine); parents reconstruct the witness call path."""
    roles: dict[str, set[tuple[str, str | None]]] = {}
    parents: dict[tuple, tuple | None] = {}
    work: list[tuple] = []

    def seed(usr, role, engine, parent=None):
        key = (usr, role, engine)
        if key in parents:
            return
        parents[key] = parent
        roles.setdefault(usr, set()).add((role, engine))
        work.append(key)

    for r in model.roots:
        seed(r.usr, r.role, r.engine)
    for usr, fi in model.funcs.items():
        if fi.cls is None and fi.name.startswith("accl_rt_"):
            if fi.name.startswith("accl_rt_create"):
                role = "init"
            elif fi.name == "accl_rt_destroy":
                role = "fini"
            else:
                role = "api"
            seed(usr, role, None)
    # destructors tear down after (or while) threads run: fini role
    for usr, fi in model.funcs.items():
        if fi.name.split("::")[-1].startswith("~"):
            seed(usr, "fini", None)

    while work:
        key = work.pop()
        usr, role, engine = key
        fi = model.funcs.get(usr)
        if fi is None:
            continue
        for cs in fi.calls:
            for tgt in cs.targets:
                tf = model.funcs.get(tgt)
                if tf is None:
                    continue
                e2 = engine
                tag = ENGINE_TAGS.get(tf.cls or "")
                if tag is not None:
                    if engine is not None and tag != engine:
                        continue  # other engine's override: unreachable
                    e2 = tag
                if tf.poe_only and e2 is not None and \
                        e2 not in tf.poe_only:
                    continue
                seed(tgt, role, e2, parent=(key, cs))
    return roles, parents


def witness_path(parents, key) -> list[str]:
    chain = []
    while key is not None:
        entry = parents.get(key)
        usr, role, engine = key
        chain.append((usr, role, engine,
                      entry[1] if entry else None))
        key = entry[0] if entry else None
    chain.reverse()
    return chain


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

def _short(path: str) -> str:
    try:
        return str(pathlib.Path(path).resolve().relative_to(REPO))
    except ValueError:
        return pathlib.Path(path).name


def _ann_for(model: Model, file: str) -> FileAnnotations:
    return model.annotations.setdefault(
        file, read_annotations(pathlib.Path(file)))


def _held_matches(held: tuple[str, ...], mu: str) -> bool:
    return any(h == mu or h.split("::")[-1] == mu for h in held)


def check_rx_reachability(model: Model, roles, parents,
                          waivers: list[str]) -> list[Diag]:
    """ACCLN101 (blocking) + ACCLN105 (ungated prints) on rx roles."""
    diags = []
    for usr, fi in model.funcs.items():
        rx_states = [(r, e) for (r, e) in roles.get(usr, ())
                     if r in NO_BLOCK_ROLES]
        if not rx_states:
            continue
        role, engine = rx_states[0]
        key = (usr, role, engine)
        chain = witness_path(parents, key)
        path_names = [model.funcs[u].name for (u, _, _, _) in chain
                      if u in model.funcs]
        root = chain[0]
        root_fi = model.funcs.get(root[0])
        root_desc = (f"rx root {root_fi.name} "
                     f"({_short(root_fi.file)}:{root_fi.line})"
                     if root_fi else "rx root")
        for b in fi.blocking:
            ann = _ann_for(model, b.file)
            reason = ann.allow(b.line, "ACCLN101")
            if reason is not None:
                waivers.append(
                    f"ACCLN101 waived at {_short(b.file)}:{b.line} "
                    f"in {fi.name}: {reason}")
                continue
            diags.append(Diag(
                "ACCLN101", _short(b.file), b.line,
                f"rx-thread role reaches {b.what}",
                detail=[root_desc,
                        "path: " + " -> ".join(path_names),
                        f"blocking site in {fi.name} at "
                        f"{_short(b.file)}:{b.line}"]))
        for p in fi.prints:
            if p.debug_gated:
                continue
            ann = _ann_for(model, p.file)
            reason = ann.allow(p.line, "ACCLN105")
            if reason is not None:
                waivers.append(
                    f"ACCLN105 waived at {_short(p.file)}:{p.line} "
                    f"in {fi.name}: {reason}")
                continue
            diags.append(Diag(
                "ACCLN105", _short(p.file), p.line,
                f"{p.what} reachable from rx-thread role outside a "
                f"debug-gated branch",
                detail=[root_desc,
                        "path: " + " -> ".join(path_names)]))
    return diags


def check_lock_order(model: Model, waivers: list[str]) -> list[Diag]:
    """ACCLN102: global lock-order acyclicity, witness rendered."""
    # transitive "may acquire" per function (spawned lambdas excluded:
    # they run on their own thread, not under the caller's locks)
    acq: dict[str, set[str]] = {u: {ev.mutex for ev in fi.locks}
                                for u, fi in model.funcs.items()}
    changed = True
    while changed:
        changed = False
        for u, fi in model.funcs.items():
            for cs in fi.calls:
                for tgt in cs.targets:
                    extra = acq.get(tgt, set()) | set(
                        model.funcs[tgt].requires
                        if tgt in model.funcs else ())
                    if not extra <= acq[u]:
                        acq[u] |= extra
                        changed = True

    edges: dict[tuple[str, str], str] = {}

    def add_edge(a, b, site):
        if a != b and (a, b) in edges:
            return
        edges[(a, b)] = site

    for u, fi in model.funcs.items():
        for ev in fi.locks:
            for h in ev.held:
                add_edge(h, ev.mutex,
                         f"{h} held at {_short(ev.file)}:{ev.line} in "
                         f"{fi.name} when acquiring {ev.mutex}")
        for cs in fi.calls:
            if not cs.held:
                continue
            for tgt in cs.targets:
                tf = model.funcs.get(tgt)
                if tf is None:
                    continue
                inner = acq.get(tgt, set()) | set(tf.requires)
                for m2 in inner:
                    for h in cs.held:
                        if m2 in tf.requires and _held_matches(cs.held, m2):
                            continue  # caller passes the held lock down
                        add_edge(h, m2,
                                 f"{h} held at {_short(cs.file)}:{cs.line} "
                                 f"in {fi.name} calling {tf.name} "
                                 f"(which may acquire {m2})")

    # cycle search (DFS with colors); self-edges are cycles of length 1
    graph: dict[str, list[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    for (a, b), site in sorted(edges.items()):
        if a == b:
            return [Diag("ACCLN102", "native", 0,
                         f"lock self-cycle on {a}",
                         detail=[site])]
    color: dict[str, int] = {}
    stack: list[str] = []

    def dfs(n):
        color[n] = 1
        stack.append(n)
        for m in sorted(graph.get(n, [])):
            if color.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if color.get(m, 0) == 0:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = 2
        return None

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc:
                detail = [" -> ".join(cyc)]
                for a, b in zip(cyc, cyc[1:]):
                    detail.append(edges[(a, b)])
                return [Diag("ACCLN102", "native", 0,
                             "lock-order cycle", detail=detail)]
    return []


def check_guarded_fields(model: Model, roles,
                         waivers: list[str]) -> list[Diag]:
    """ACCLN103: annotation coverage + access discipline + REQUIRES
    call-site proof."""
    diags = []
    # 1. every non-exempt field of an audited struct carries an annotation
    for (cls, fld), f in sorted(model.fields.items()):
        if cls not in model.audited or f.exempt:
            continue
        if f.annotation is None:
            ann = _ann_for(model, f.file)
            reason = ann.allow(f.line, "ACCLN103")
            if reason is not None:
                waivers.append(
                    f"ACCLN103 waived at {_short(f.file)}:{f.line} "
                    f"({cls}::{fld}): {reason}")
                continue
            diags.append(Diag(
                "ACCLN103", _short(f.file), f.line,
                f"shared field {cls}::{fld} has no ACCL_GUARDED_BY / "
                f"ACCL_INIT_CONST / ACCL_ROLE_ONLY annotation "
                f"(type: {f.type_spelling})"))

    # 2. every access honors the annotation
    for usr, fi in model.funcs.items():
        rset = {r for (r, _) in roles.get(usr, ())}
        if not rset:
            continue  # unreachable from any root: nothing to prove
        single = rset <= (INIT_ROLES | FINI_ROLES)
        for acc in fi.accesses:
            f = model.fields.get((acc.cls, acc.fld))
            if f is None or f.exempt or f.annotation is None or \
                    acc.cls not in model.audited:
                continue
            kind, arg = f.annotation
            ok = True
            why = ""
            if kind == "GUARDED_BY":
                ok = single or _held_matches(acc.held, arg)
                why = (f"requires {arg}; held: "
                       f"{list(acc.held) or 'nothing'}")
            elif kind == "INIT_CONST":
                ok = (not acc.write) or rset <= INIT_ROLES
                why = "init-const field written outside the init phase"
            elif kind == "ROLE_ONLY":
                allowed = {s.strip() for s in arg.split(",")}
                ok = rset <= (allowed | INIT_ROLES | FINI_ROLES)
                why = (f"restricted to role(s) {sorted(allowed)}; "
                       f"accessed from {sorted(rset)}")
            if ok:
                continue
            ann = _ann_for(model, acc.file)
            reason = ann.allow(acc.line, "ACCLN103")
            if reason is not None:
                waivers.append(
                    f"ACCLN103 waived at {_short(acc.file)}:{acc.line} "
                    f"({acc.cls}::{acc.fld} in {fi.name}): {reason}")
                continue
            diags.append(Diag(
                "ACCLN103", _short(acc.file), acc.line,
                f"{'write to' if acc.write else 'access to'} "
                f"{acc.cls}::{acc.fld} in {fi.name} violates "
                f"ACCL_{kind}", detail=[why,
                                        f"roles: {sorted(rset)}"]))

        # 3. calling an ACCL_REQUIRES function without the lock
        for cs in fi.calls:
            for tgt in cs.targets:
                # a lambda's synthetic definition-site edge is not an
                # invocation: its REQUIRES binds real call sites, which
                # resolve through operator() and are checked via the
                # body's held-set, not here
                if tgt.startswith("lambda@"):
                    continue
                tf = model.funcs.get(tgt)
                if tf is None or not tf.requires:
                    continue
                for mu in tf.requires:
                    if single or _held_matches(cs.held, mu) or \
                            mu in fi.requires:
                        continue
                    ann = _ann_for(model, cs.file)
                    reason = ann.allow(cs.line, "ACCLN103")
                    if reason is not None:
                        waivers.append(
                            f"ACCLN103 waived at "
                            f"{_short(cs.file)}:{cs.line} "
                            f"(call {tf.name}): {reason}")
                        continue
                    diags.append(Diag(
                        "ACCLN103", _short(cs.file), cs.line,
                        f"{fi.name} calls {tf.name} without holding "
                        f"{mu} (declared ACCL_REQUIRES({mu}))",
                        detail=[f"held: {list(cs.held) or 'nothing'}"]))
    return diags


def check_seam(files: dict[pathlib.Path, str]) -> list[Diag]:
    """ACCLN104 over {path: effective-basename} (fixtures may pose as a
    real TU via // AS_FILE). Pure text — no libclang needed."""
    diags = []
    for path, as_name in files.items():
        try:
            lines = path.read_text().splitlines()
        except OSError:
            continue
        for rule in SEAM_RULES:
            if rule["file"] != as_name:
                continue
            inc = rule.get("forbid_include")
            syms = rule.get("forbid_symbols", [])
            sym_re = re.compile(
                r"\b(" + "|".join(map(re.escape, syms)) + r")\b") \
                if syms else None
            for i, ln in enumerate(lines, start=1):
                code = ln.split("//", 1)[0]
                if inc and re.search(inc, code):
                    diags.append(Diag(
                        "ACCLN104", _short(str(path)), i,
                        f"seam violation: {rule['reason']}",
                        detail=[ln.strip()]))
                elif sym_re and sym_re.search(code):
                    diags.append(Diag(
                        "ACCLN104", _short(str(path)), i,
                        f"seam violation: session-side symbol "
                        f"'{sym_re.search(code).group(1)}' in "
                        f"{as_name} ({rule['reason']})",
                        detail=[ln.strip()]))
    return diags


def run_rules(model: Model, seam_files: dict[pathlib.Path, str],
              waivers: list[str]) -> list[Diag]:
    diags: list[Diag] = []
    for err in model.parse_errors:
        diags.append(Diag("ACCLN100", "native", 0,
                          f"translation unit failed to parse: {err}"))
    if not model.parse_errors:
        roles, parents = propagate_roles(model)
        diags += check_rx_reachability(model, roles, parents, waivers)
        diags += check_lock_order(model, waivers)
        diags += check_guarded_fields(model, roles, waivers)
    diags += check_seam(seam_files)
    return diags


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------

def run_tree(cindex, verbose: bool = False) -> int:
    model = build_model(cindex, TREE_TUS,
                        [str(NATIVE / "include")])
    waivers: list[str] = []
    seam = {p: p.name for p in TREE_TUS}
    diags = run_rules(model, seam, waivers)
    for d in diags:
        print(d.render())
    for w in waivers:
        print(f"  [waiver] {w}")
    n_roles = len(model.roots)
    print(f"native_check --tree: {len(TREE_TUS)} TUs, "
          f"{len(model.funcs)} functions, {n_roles} thread roots, "
          f"{len(waivers)} waiver(s), {len(diags)} diagnostic(s)")
    return 1 if diags else 0


def run_corpus(cindex, corpus_dir: pathlib.Path,
               verbose: bool = False) -> int:
    fixtures = sorted(corpus_dir.glob("*.cpp"))
    if not fixtures:
        print(f"no fixtures under {corpus_dir}", file=sys.stderr)
        return 1
    bad = 0
    n_reject = 0
    for fx in fixtures:
        text = fx.read_text()
        want: set[str] = set()
        for m in EXPECT_RE.finditer(text):
            want |= {c.strip() for c in m.group(1).split(",")
                     if c.strip()}
        as_m = AS_FILE_RE.search(text)
        as_name = as_m.group(1) if as_m else fx.name
        model = build_model(cindex, [fx], [str(NATIVE / "include")])
        waivers: list[str] = []
        diags = run_rules(model, {fx: as_name}, waivers)
        got = {d.code for d in diags}
        ok = got == want
        if want:
            n_reject += 1
        status = "ok" if ok else "MISMATCH"
        kind = ("expect " + ",".join(sorted(want))) if want else "clean"
        print(f"  {fx.name}: {kind} -> "
              f"{','.join(sorted(got)) or 'clean'} [{status}]")
        if not ok:
            bad += 1
            for d in diags:
                print("    " + d.render().replace("\n", "\n    "))
    print(f"corpus: {len(fixtures)} fixtures "
          f"({n_reject} known-bad, {len(fixtures) - n_reject} good), "
          f"{bad} mismatch(es)")
    return 1 if bad else 0


def run_seam_only() -> int:
    diags = check_seam({p: p.name for p in TREE_TUS})
    for d in diags:
        print(d.render())
    if not diags:
        print("seamcheck: transport.cpp is clean of reliability "
              "internals")
    return 1 if diags else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--tree", action="store_true",
                    help="certify the live native sources")
    ap.add_argument("--corpus", nargs="?", const=str(DEFAULT_CORPUS),
                    default=None, metavar="DIR",
                    help="replay the fixture corpus (default "
                         "tools/native_lint_corpus/)")
    ap.add_argument("--seam", action="store_true",
                    help="ACCLN104 include/symbol rules only (the "
                         "`make -C native seamcheck` wrapper)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.seam and not (args.tree or args.corpus):
        return run_seam_only()
    if not (args.tree or args.corpus or args.seam):
        ap.print_help()
        return 2

    cindex = load_cindex()
    if cindex is None:
        print("native_check: FAIL (libclang is required for --tree/"
              "--corpus; --seam runs without it)", file=sys.stderr)
        return 1

    rc = 0
    if args.corpus:
        rc |= run_corpus(cindex, pathlib.Path(args.corpus), args.verbose)
    if args.tree:
        rc |= run_tree(cindex, args.verbose)
    if args.seam and (args.tree or args.corpus):
        rc |= run_seam_only()
    return rc


if __name__ == "__main__":
    sys.exit(main())
