// EXPECT: ACCLN101
//
// The PR 14 bug, reduced: the rx thread handles a NACK by
// RETRANSMITTING INLINE through the blocking send path. If the peer's
// socket is full because the peer is itself blocked sending to us,
// neither rx loop ever drains — the mutual-wedge liveness hazard the
// rx no-blocking rule exists to forbid.
#include <thread>
#include <vector>

static bool send_all(int fd, const void *p, unsigned n) {
  (void)fd; (void)p; (void)n;  // flush loop elided: the NAME is the contract
  return true;
}

struct Runtime {
  std::vector<std::thread> rx_threads_;

  void retransmit(unsigned seqn) { send_all(3, &seqn, sizeof seqn); }

  void rx_loop() {
    for (;;) {
      unsigned nack_seqn = 0;
      retransmit(nack_seqn);  // blocking send ON the rx thread
    }
  }

  void start() {
    rx_threads_.emplace_back([this] { rx_loop(); });
  }
};
