// (clean twin of bad_unlocked_access: the same entry point takes the
// declared lock before touching the field.)
#include <mutex>

struct Counters {  // ACCL_AUDITED
  std::mutex mu;
  long landed = 0;  // ACCL_GUARDED_BY(mu)
};

extern "C" void accl_rt_poke(Counters *c) {
  std::lock_guard<std::mutex> g(c->mu);
  c->landed++;
}
