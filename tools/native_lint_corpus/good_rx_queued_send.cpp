// (clean twin of bad_rx_blocking_send: the rx thread only QUEUES the
// retransmit; a sender role performs the blocking send. The same
// send_all site is fine there — the rule is about rx roles.)
#include <mutex>
#include <thread>
#include <vector>

static bool send_all(int fd, const void *p, unsigned n) {
  (void)fd; (void)p; (void)n;
  return true;
}

struct Runtime {
  std::vector<std::thread> rx_threads_;
  std::thread rely_thread;
  std::mutex rely_mu;
  std::vector<unsigned> retx_q;  // ACCL_GUARDED_BY(rely_mu)

  void rx_loop() {
    for (;;) {
      unsigned nack_seqn = 0;
      std::lock_guard<std::mutex> g(rely_mu);
      retx_q.push_back(nack_seqn);  // queue, never send
    }
  }

  void rely_loop() {
    for (;;) {
      unsigned seqn;
      {
        std::lock_guard<std::mutex> g(rely_mu);
        if (retx_q.empty()) continue;
        seqn = retx_q.back();
        retx_q.pop_back();
      }
      send_all(3, &seqn, sizeof seqn);  // sender role: may block
    }
  }

  void start() {
    rx_threads_.emplace_back([this] { rx_loop(); });
    rely_thread = std::thread([this] { rely_loop(); });
  }
};
