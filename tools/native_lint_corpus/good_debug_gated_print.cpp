// (clean twin of bad_rx_ungated_print: the same fprintf behind the
// cached debug flag is fine — that is what the flag is for.)
#include <cstdio>
#include <thread>
#include <vector>

struct Runtime {
  std::vector<std::thread> rx_threads_;
  bool debug_on = false;  // ACCL_INIT_CONST

  void rx_loop() {
    for (;;) {
      if (debug_on) std::fprintf(stderr, "rx: frame dropped\n");
    }
  }

  void start() {
    rx_threads_.emplace_back([this] { rx_loop(); });
  }
};
