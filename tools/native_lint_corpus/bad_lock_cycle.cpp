// EXPECT: ACCLN102
//
// Classic AB/BA deadlock: the sequencer flushes completions holding
// call_mu then comp_mu; the waiter re-queues holding comp_mu then
// call_mu. Each order alone is fine — the CYCLE in the global lock
// graph is the bug, and the diagnostic renders it as a witness.
#include <mutex>

struct Runtime {
  std::mutex call_mu;
  std::mutex comp_mu;
  int pending = 0;    // ACCL_GUARDED_BY(call_mu)
  int completed = 0;  // ACCL_GUARDED_BY(comp_mu)

  void flush() {  // call_mu -> comp_mu
    std::lock_guard<std::mutex> g(call_mu);
    pending--;
    std::lock_guard<std::mutex> h(comp_mu);
    completed++;
  }

  void requeue() {  // comp_mu -> call_mu: closes the cycle
    std::lock_guard<std::mutex> g(comp_mu);
    completed--;
    std::lock_guard<std::mutex> h(call_mu);
    pending++;
  }
};
