// EXPECT: ACCLN105
//
// An unconditional fprintf on the rx path: under a chaos soak every
// dropped frame becomes a write(2) on the hot loop. Diagnostics from
// rx roles must sit behind the cached debug flag.
#include <cstdio>
#include <thread>
#include <vector>

struct Runtime {
  std::vector<std::thread> rx_threads_;

  void rx_loop() {
    for (;;) {
      std::fprintf(stderr, "rx: frame dropped\n");  // ungated
    }
  }

  void start() {
    rx_threads_.emplace_back([this] { rx_loop(); });
  }
};
