// (clean twin of bad_lock_cycle: both paths honor the one global
// order call_mu -> comp_mu, so the lock graph is acyclic.)
#include <mutex>

struct Runtime {
  std::mutex call_mu;
  std::mutex comp_mu;
  int pending = 0;    // ACCL_GUARDED_BY(call_mu)
  int completed = 0;  // ACCL_GUARDED_BY(comp_mu)

  void flush() {
    std::lock_guard<std::mutex> g(call_mu);
    pending--;
    std::lock_guard<std::mutex> h(comp_mu);
    completed++;
  }

  void requeue() {
    std::lock_guard<std::mutex> g(call_mu);
    pending++;
    std::lock_guard<std::mutex> h(comp_mu);
    completed--;
  }
};
