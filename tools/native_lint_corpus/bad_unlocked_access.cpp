// EXPECT: ACCLN103
//
// A guarded field touched without its lock from a live entry point:
// the annotation is a claim, and every access must prove it.
#include <mutex>

struct Counters {  // ACCL_AUDITED
  std::mutex mu;
  long landed = 0;  // ACCL_GUARDED_BY(mu)
};

extern "C" void accl_rt_poke(Counters *c) {
  c->landed++;  // api role, mu not held
}
