// EXPECT: ACCLN103
//
// An audited struct with a bare shared field: no ACCL_GUARDED_BY /
// ACCL_INIT_CONST / ACCL_ROLE_ONLY claim means no proof obligation was
// even stated — the honest-audit half of the rule.
#include <mutex>

struct Counters {  // ACCL_AUDITED
  std::mutex mu;
  long landed = 0;
};
