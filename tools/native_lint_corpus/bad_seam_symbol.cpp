// EXPECT: ACCLN104
// AS_FILE: transport.cpp
//
// A transport TU reaching for session-side reliability internals: the
// POE seam carries already-built frames only, so CRC (and retransmit
// retention) must never leak below it.
#if 0
#include "reliability.h"
#endif

unsigned crc32c(unsigned seed, const void *p, unsigned n);

static unsigned checksum_frame(const void *p, unsigned n) {
  return crc32c(0u, p, n);
}

unsigned frame_checksum_entry(const void *p, unsigned n) {
  return checksum_frame(p, n);
}
