// AS_FILE: transport.cpp
// (clean twin of bad_seam_symbol: a transport TU that stays below the
// seam — raw byte movement, no reliability symbols, no CRC.)
#include <cstring>

bool copy_frame(void *dst, const void *src, unsigned n) {
  std::memcpy(dst, src, n);
  return true;
}
