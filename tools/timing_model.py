#!/usr/bin/env python3
"""Calibrate the per-hop timing model from measured sweeps and validate
the tuning-register defaults as performance crossovers.

The cclo_sim role (reference test/model/simulator/cclo_sim.cpp:25-80):
a second target answering "how long should this schedule take" — here an
alpha-beta model (sequencer/timing.py) fitted to the emulator benchmark
CSV (tools/bench_emulator.py) and, when present, the TPU profile.

Writes accl_log/timing_model.json:
  { link params, per-row predicted-vs-measured, tuning crossovers }
"""

import csv
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from accl_tpu.constants import Operation, TuningParams  # noqa: E402
from accl_tpu.sequencer.plan import select_algorithm  # noqa: E402
from accl_tpu.sequencer.timing import (  # noqa: E402
    calibrate,
    coefficients,
    coefficients_aggregate,
    predict,
    tuning_crossovers,
)

OPS = {"allreduce": Operation.allreduce, "bcast": Operation.bcast,
       "allgather": Operation.allgather, "reduce": Operation.reduce,
       "gather": Operation.gather, "scatter": Operation.scatter,
       "alltoall": Operation.alltoall,
       "reduce_scatter": Operation.reduce_scatter}

# the emulator bench's eager/rx geometry, single-sourced from the sweep
# tool so calibration can never drift from what the sweep actually ran
from tools.bench_emulator import (  # noqa: E402
    FIT_MAX_WORLD,
    MAX_EAGER,
    RX_BUF,
)


def load_rows(path: pathlib.Path, default_world: int):
    """Rows inside the calibration domain (worlds <= FIT_MAX_WORLD —
    see tools/bench_emulator.py: larger worlds are scale evidence, not
    fit input), plus the count of rows excluded by the domain."""
    rows = []
    beyond = 0
    with open(path) as f:
        for r in csv.DictReader(f):
            op = OPS.get(r["Collective"])
            if op is None:
                continue
            world = int(r.get("World") or default_world)
            if world > FIT_MAX_WORLD:
                beyond += 1
                continue
            rows.append((op, int(r["Bytes"]), float(r["Seconds"]), world))
    return rows, beyond


def tpu_tier(profile: pathlib.Path) -> dict | None:
    """Second calibration tier from the committed on-chip profile
    (bench.py -> accl_log/profile.csv): the reference calibrates its
    simulator against silicon the same way (cycles x 4ns,
    xrtdevice.cpp:248). Measured quantities only:

      - dispatch alpha: alpha-beta fit over the w1 compiled-collective
        lanes (host-observed per-dispatch cost through the relay; on a
        dispatch-bound single chip the fit clamps beta to ~inf, which is
        itself the finding);
      - HBM beta: the streaming-regime combine rows (payload GB/s).

    ICI beta needs a multi-chip slice and is reported as unmeasured
    rather than assumed."""
    if not profile.exists():
        return None
    disp, hbm = [], []
    with open(profile) as f:
        for r in csv.DictReader(f):
            if r.get("Regime") == "noise":
                continue  # resolution floor, not a measurement
            if "_w1_dispatch_datapath" in r["Test"]:
                disp.append((1.0, float(r["Bytes"]), float(r["Seconds"])))
            elif r["Test"] == "combine_sum_fp32" and \
                    r.get("Regime") == "stream":
                hbm.append(float(r["GBps"]))
    if not disp:
        return None
    params = calibrate(disp)
    alpha = params.alpha
    if params.beta >= 1e11:
        # pure-latency fit (beta clamped at inf): the least-squares alpha
        # can overshoot every sample when the raw slope was negative —
        # the median dispatch time is the honest constant
        times = sorted(t for _, _, t in disp)
        alpha = times[len(times) // 2]
    tier = {
        "source": str(profile.name),
        "dispatch_alpha_us": alpha * 1e6,
        "dispatch_beta_gbps": (None if params.beta >= 1e11
                               else params.beta / 1e9),
        "hbm_stream_gbps": (sorted(hbm)[len(hbm) // 2] if hbm else None),
        "ici_beta_gbps": None,
        "note": "ici unmeasured: single-chip tunnel; w1 lanes are "
                "dispatch-bound so datapath beta clamps to inf when "
                "dispatch swamps it",
    }
    # crossovers under TPU dispatch costs: latency this high pushes the
    # flat->tree switch far right (a projection labeled as such — the
    # wire beta is the HBM bound, an upper limit on any future ICI tier)
    if tier["hbm_stream_gbps"]:
        from accl_tpu.sequencer.timing import LinkParams

        proj = LinkParams(alpha=alpha,
                          beta=tier["hbm_stream_gbps"] * 1e9)
        tier["projected_crossovers"] = tuning_crossovers(proj, world=8)
    return tier


def _fit_per_collective(meta):
    """meta: (op, plan, count, nbytes, secs, world). One LinkParams per
    collective, fitted on the AGGREGATE (serialized-host) cost shape —
    see timing.coefficients_aggregate: the emulator world timeshares one
    CI core, so wall time tracks total moved bytes/messages, and
    per-collective fits absorb each algorithm family's distinct
    per-message cost (a bcast tree hop is a light relay; an allgather
    hop is a full chunk landing)."""
    groups = {}
    for op, plan, count, nbytes, secs, world in meta:
        m, b = coefficients_aggregate(op, plan, count, 4, world,
                                      rx_buf_bytes=RX_BUF)
        groups.setdefault(op.name, []).append((m, b, secs))
    return {name: calibrate(samples) for name, samples in groups.items()}


def _predict_row(fits, op, plan, count, nbytes, world):
    params = fits[op.name]
    return predict(params, op, plan, count, 4, world, rx_buf_bytes=RX_BUF,
                   aggregate=True)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=4,
                    help="world size of the sweep, used only for CSVs "
                         "written before the World column existed")
    args = ap.parse_args()

    src = REPO / "accl_log" / "emu_bench.csv"
    if not src.exists():
        print(f"no {src}; run tools/bench_emulator.py first",
              file=sys.stderr)
        return 1
    rows, main_beyond = load_rows(src, args.world)
    if not rows:
        print(f"{src} has no usable collective rows; re-run "
              "tools/bench_emulator.py", file=sys.stderr)
        return 1
    tuning = TuningParams.default()
    meta = []
    for op, nbytes, secs, world in rows:
        count = nbytes // 4
        plan = select_algorithm(op, count, 4, world,
                                max_eager_size=MAX_EAGER,
                                eager_rx_buf_size=RX_BUF, tuning=tuning)
        meta.append((op, plan, count, nbytes, secs, world))

    # per-collective aggregate-shape fits on the full sweep (the
    # reported model), plus leave-one-world-out cross-validation: each
    # world's rows are predicted by a model fitted WITHOUT them, so the
    # reported holdout error measures generalization, not curve
    # memorization.
    fits = _fit_per_collective(meta)
    report = []
    for op, plan, count, nbytes, secs, world in meta:
        pred = _predict_row(fits, op, plan, count, nbytes, world)
        report.append({
            "collective": op.name, "bytes": nbytes, "world": world,
            "algorithm": plan.algorithm.name,
            "measured_s": secs, "predicted_s": pred,
            "ratio": pred / secs if secs else None,
        })
    ratios = sorted(r["ratio"] for r in report if r["ratio"])
    med = ratios[len(ratios) // 2]

    holdout_ratios = []
    worlds = sorted({w for *_x, w in meta})
    if len(worlds) >= 2:
        for held in worlds:
            train = [m for m in meta if m[5] != held]
            test = [m for m in meta if m[5] == held]
            try:
                hfits = _fit_per_collective(train)
            except Exception:
                continue
            for op, plan, count, nbytes, secs, world in test:
                if op.name not in hfits or not secs:
                    continue
                pred = predict(hfits[op.name], op, plan, count, 4, world,
                               rx_buf_bytes=RX_BUF, aggregate=True)
                holdout_ratios.append(pred / secs)
    holdout_ratios.sort()
    med_holdout = (holdout_ratios[len(holdout_ratios) // 2]
                   if holdout_ratios else None)

    # per-POE tiers: each transport has its own link parameters (the
    # datagram POE pays per-packet costs, the intra-process POE has no
    # sockets at all) — fit each sweep that exists separately, the
    # per-calibration-target posture of the reference's simulator/hw
    # split
    def fit_tier(csv_name: str) -> dict | None:
        src = REPO / "accl_log" / csv_name
        if not src.exists():
            return None
        # the calibration domain (worlds <= FIT_MAX_WORLD) is enforced
        # by load_rows, shared with the main fit: w32 local rows fit at
        # ~1.6x median when pooled — superlinear scheduling at 32
        # threads on one core — so they stay out of every tier
        trows, skipped = load_rows(src, args.world)
        tmeta = []
        for op, nbytes, secs, world in trows:
            count = nbytes // 4
            plan = select_algorithm(op, count, 4, world,
                                    max_eager_size=MAX_EAGER,
                                    eager_rx_buf_size=RX_BUF,
                                    tuning=tuning)
            tmeta.append((op, plan, count, nbytes, secs, world))
        if not tmeta:
            return None
        tfits = _fit_per_collective(tmeta)
        tratios = sorted(
            _predict_row(tfits, op, plan, count, nbytes, world) / secs
            for op, plan, count, nbytes, secs, world in tmeta if secs)
        return {
            "source": csv_name,
            "link_per_collective": {
                name: {"alpha_us": p.alpha * 1e6,
                       "beta_gbps": p.beta / 1e9}
                for name, p in sorted(tfits.items())
            },
            "fit": {"rows": len(tmeta),
                    "rows_beyond_domain": skipped,
                    "calibration_domain": f"worlds <= {FIT_MAX_WORLD}",
                    "median_pred_over_meas":
                        (tratios[len(tratios) // 2] if tratios else None)},
        }

    local_fits = fit_tier("emu_bench_local.csv")
    udp_fits = fit_tier("emu_bench_udp.csv")

    # Crossovers reason over CRITICAL-PATH shapes (the parallel-hardware
    # posture the registers exist for); feed them the bcast link — the
    # root-serialized collective whose aggregate and critical shapes
    # coincide, so its fitted alpha/beta are genuine per-message /
    # per-byte costs of this host rather than world-summed ones.
    cross_params = fits.get("bcast") or next(iter(fits.values()))
    cross = tuning_crossovers(cross_params, world=8)
    tpu = tpu_tier(REPO / "accl_log" / "profile.csv")
    out = {
        "source": str(src.relative_to(REPO)),
        "cost_shape": "aggregate (serialized single-core host; see "
                      "timing.coefficients_aggregate)",
        "link_per_collective": {
            name: {"alpha_us": p.alpha * 1e6, "beta_gbps": p.beta / 1e9,
                   "rows": sum(1 for r in report
                               if r["collective"] == name)}
            for name, p in sorted(fits.items())
        },
        "fit": {"rows": len(report), "median_pred_over_meas": med,
                "median_holdout_pred_over_meas": med_holdout,
                "holdout": "leave-one-world-out",
                "worlds": worlds,
                "rows_beyond_domain": main_beyond,
                "calibration_domain": f"worlds <= {FIT_MAX_WORLD}"},
        "rows": report,
        "local_poe_tier": local_fits,
        "udp_poe_tier": udp_fits,
        "tuning_crossovers": cross,
        "tpu_tier": tpu,
        "reference_defaults": {
            "bcast_flat_tree_max_ranks": 3,
            "reduce_flat_tree_max_ranks": 4,
            "reduce_flat_tree_max_count_bytes": 32 * 1024,
            "gather_flat_tree_max_count_bytes": 32 * 1024,
        },
    }
    dst = REPO / "accl_log" / "timing_model.json"
    dst.write_text(json.dumps(out, indent=1) + "\n")
    for reg, p in sorted(fits.items()):
        print(f"{reg}: alpha={p.alpha*1e6:.1f}us "
              f"beta={p.beta/1e9:.3f}GB/s")
    print(f"median pred/meas={med:.2f} holdout={med_holdout and round(med_holdout, 2)}"
          f" -> {dst.relative_to(REPO)}")
    print(f"crossovers: {cross}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
