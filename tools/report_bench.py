"""Merge every benchmark artifact into one human-readable report.

The role of the reference's result pipeline — parse_bench_results.py
(test/host/xrt) collating the per-rank sweep CSVs and the Coyote
run_scripts/plot.py summarizing latency/throughput logs against
baselines — as a single markdown emitter:

  accl_log/profile.csv       on-chip TPU lanes (combine, dispatch sweeps)
  accl_log/profile_cpu.csv   same lanes, CPU-fallback regime (labeled)
  accl_log/emu_bench.csv     native-emulator transport sweep (per world)
  accl_log/emu_bench_udp.csv same over the sessionless datagram POE
  accl_log/flagship*.csv     flagship train-step lane (tokens/s, MFU)
  accl_log/timing_model.json alpha-beta model fit + selection crossovers

Output: accl_log/REPORT.md (and the same text to stdout). Missing
artifacts are reported as absent, never invented.
"""

from __future__ import annotations

import csv
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "accl_log"
sys.path.insert(0, str(REPO))
from bench import BASELINE_GBPS  # noqa: E402  (single authoritative value)


def _read_csv(name: str) -> list[dict]:
    p = LOG / name
    if not p.exists():
        return []
    with open(p) as f:
        return list(csv.DictReader(f))


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if n >= div:
            v = n / div
            return f"{v:.0f} {unit}" if v == int(v) else f"{v:.1f} {unit}"
    return f"{n} B"


def section_trajectory(out: list[str]) -> None:
    """The headline-metric trajectory across committed bench rounds
    (BENCH_r*.json at the repo root), labeled by the artifact's
    `platform` field — "tpu" rounds are on-chip measurements comparable
    to each other and to the pinned TPU artifact; "cpu-fallback" rounds
    are functional-regime noise recorded because the TPU was
    unreachable, and must never be read as a perf trend. Every
    committed round carries the explicit schema field (r01-r05 were
    backfilled); a round genuinely missing it renders `?*` — the label
    is never recovered from prose."""
    rounds = []
    prev_metric = None
    for p in sorted(REPO.glob("BENCH_r*.json")):
        try:
            d = json.loads(p.read_text())
        except (OSError, ValueError):
            continue
        parsed = d.get("parsed") or {}
        platform = parsed.get("platform")
        if platform is None:
            platform = "?*"
        # a round whose headline cell diverges from the previous
        # round's (a renamed or newly-added bench section) must say so
        # explicitly: rendering its value on the same trajectory row
        # set reads as a continuous series of one metric, which it is
        # not — the silent-gap failure this marker replaces
        metric = parsed.get("metric")
        note = ""
        if prev_metric is not None and metric is not None \
                and metric != prev_metric:
            note = "new-cell"
        if metric is not None:
            prev_metric = metric
        rounds.append((p.name, parsed.get("value"), parsed.get("unit", ""),
                       platform, note))
    if not rounds:
        return
    out.append("## Headline trajectory (`BENCH_r*.json`)\n")
    out.append("| Round | Value | Unit | Platform | Note |"
               "\n|---|---|---|---|---|")
    for name, value, unit, platform, note in rounds:
        out.append(f"| {name} | {value} | {unit} | {platform} | "
                   f"{note} |")
    out.append("")
    if any(platform == "?*" for _, _, _, platform, _ in rounds):
        out.append("`?*` = artifact genuinely missing the `platform` "
                   "schema field. ")
    if any(note == "new-cell" for *_, note in rounds):
        out.append("`new-cell` = the round's headline metric differs "
                   "from the previous round's (renamed/added bench "
                   "cell): values across that boundary are not one "
                   "trajectory. ")
    out.append("Only same-platform rounds are comparable; cpu-fallback "
               "values are not a regression signal.\n")


def section_tpu(out: list[str]) -> None:
    rows = _read_csv("profile.csv")
    out.append("## On-chip TPU lanes (`profile.csv`)\n")
    if not rows:
        out.append("*absent — no TPU run committed*\n")
        return
    stream = [r for r in rows if r.get("Regime") == "stream"
              and r["Test"] == "combine_sum_fp32"]
    if stream:
        g = float(stream[-1]["GBps"])
        out.append(
            f"**Headline:** combine lane {g:.1f} GB/s payload at the "
            f"{_fmt_bytes(int(stream[-1]['Bytes']))} HBM-streaming point "
            f"= **{g / BASELINE_GBPS:.1f}x** the reference's "
            f"{BASELINE_GBPS} GB/s line rate.\n")
    out.append("| Test | Bytes | GB/s | Regime |\n|---|---|---|---|")
    for r in rows:
        out.append(f"| {r['Test']} | {_fmt_bytes(int(r['Bytes']))} | "
                   f"{float(r['GBps']):.2f} | {r.get('Regime', '')} |")
    out.append("")
    out.append("`latency` rows measure dispatch/VMEM-resident time, not "
               "bandwidth; only `stream` rows are HBM throughput; `noise` "
               "rows never resolved above relay jitter — their Seconds is "
               "the jitter resolution floor (an upper bound on the true "
               "time, so GB/s is a lower bound), not a measurement.\n")

    cpu = _read_csv("profile_cpu.csv")
    if cpu:
        out.append("### CPU-fallback lanes (`profile_cpu.csv`)\n")
        out.append("Functional regime only (written when the TPU is "
                   "unreachable; can never clobber the TPU artifact).\n")
        out.append("| Test | Bytes | GB/s | Regime |\n|---|---|---|---|")
        for r in cpu:
            out.append(f"| {r['Test']} | {_fmt_bytes(int(r['Bytes']))} | "
                       f"{float(r['GBps']):.2f} | {r.get('Regime', '')} |")
        out.append("")


def _agg_wire_gbps(r: dict) -> str:
    """Aggregate wire-bytes bandwidth of one sweep row: the TOTAL bytes
    the planned schedule moves across all ranks
    (timing.coefficients_aggregate) over the measured seconds — the
    volume-honest column the r5 verdict asked for. Payload GB/s
    understates collectives that move (P-1)x their payload; this one
    does not."""
    try:
        from accl_tpu.telemetry.native import aggregate_wire_gbps

        v = aggregate_wire_gbps(r["Collective"], int(r["Bytes"]),
                                int(r["World"]), float(r["Seconds"]))
        return f"{v:.3f}"
    except (KeyError, ValueError, ImportError):
        return "-"


def section_emulator(out: list[str]) -> None:
    for name, title in (("emu_bench.csv", "session TCP mesh"),
                        ("emu_bench_udp.csv", "sessionless datagram POE"),
                        ("emu_bench_local.csv",
                         "intra-process direct-call POE")):
        rows = _read_csv(name)
        out.append(f"## Native emulator sweep — {title} (`{name}`)\n")
        if not rows:
            out.append("*absent*\n")
            continue
        worlds = sorted({int(r["World"]) for r in rows})
        wire = ("direct-call delivery between in-process ranks, no "
                "sockets" if "local" in name else "real sockets on one "
                "host")
        out.append(f"Worlds swept: {worlds}. Functional-CI numbers "
                   f"({wire}), not hardware. GB/s is payload over "
                   "seconds; AggWire GB/s is the schedule's TOTAL "
                   "cross-rank wire bytes (timing.coefficients_aggregate)"
                   " over the same seconds — the volume the serialized "
                   "host actually moved.\n")
        out.append("| Collective | Protocol | Bytes | World | GB/s | "
                   "AggWire GB/s |\n|---|---|---|---|---|---|")
        for r in rows:
            out.append(
                f"| {r['Collective']} | {r['Protocol']} | "
                f"{_fmt_bytes(int(r['Bytes']))} | {r['World']} | "
                f"{float(r['GBps']):.3f} | {_agg_wire_gbps(r)} |")
        out.append("")


def section_flagship(out: list[str]) -> None:
    out.append("## Flagship train step\n")
    any_row = False
    for name, regime in (("flagship.csv", "TPU"),
                         ("flagship_cpu.csv", "CPU (functional)")):
        rows = _read_csv(name)
        if not rows:
            continue
        any_row = True
        r = rows[-1]
        mfu = r.get("MFUpct", "nan")
        mfu_s = "" if mfu in ("nan", "") else f", MFU {float(mfu):.1f}%"
        out.append(
            f"- **{regime}**: {int(r['NParams']) / 1e6:.1f}M params, "
            f"{float(r['SecPerStep']) * 1e3:.2f} ms/step, "
            f"{float(r['TokensPerSec']):.0f} tokens/s{mfu_s}")
    if not any_row:
        out.append("*absent*")
    out.append("")
    dec = False
    for name, regime in (("decode.csv", "TPU"),
                         ("decode_cpu.csv", "CPU (functional)")):
        rows = _read_csv(name)
        if not rows:
            continue
        if not dec:
            out.append("## Flagship incremental decode (KV cache)\n")
            dec = True
        r = rows[-1]
        noise = ("" if r.get("Regime", "ok") == "ok"
                 else " (NOISE: below timing resolution, a bound only)")
        out.append(
            f"- **{regime}**: batch {r['Batch']}, context {r['Context']}, "
            f"{float(r['SecPerStep']) * 1e3:.3f} ms/token-step, "
            f"{float(r['TokensPerSec']):.0f} tokens/s{noise}")
    if dec:
        out.append("")


def section_serving(out: list[str]) -> None:
    """The interactive-serving lane (`bench.py --serve-gate` verdict):
    fused-vs-eager decode step, continuous-batching throughput/tail,
    the calibrated lat-cell selection, and the shaped-WAN soak.
    CPU-emulator numbers — the framework's own seams, not hardware."""
    p = LOG / "serve_gate.json"
    out.append("## Interactive serving — KV-decode step "
               "(`serve_gate.json`)\n")
    if not p.exists():
        out.append("*absent — no serve-gate run committed*\n")
        return
    try:
        d = json.loads(p.read_text())
    except ValueError:
        out.append("*unreadable*\n")
        return
    parity = d.get("parity", {})
    tail = d.get("step_tail_ms", {})
    wan = d.get("wan_step_tail_ms", {})
    lat = d.get("lat_cell", {})
    fails = d.get("fails", [])
    out.append(
        f"**Headline:** fused one-dispatch decode step "
        f"{d.get('fused_ms_per_step', '?')} ms vs eager "
        f"layer-by-layer {d.get('eager_ms_per_step', '?')} ms = "
        f"**{d.get('fused_speedup', '?')}x** (floor "
        f"{d.get('fused_speedup_floor', '?')}x), "
        f"{d.get('tokens_per_s', '?')} tokens/s at "
        f"{d.get('batch_slots', '?')} slots. Platform: "
        f"{d.get('platform', '?')} — functional regime, not a "
        "hardware claim.\n")
    out.append("| Lane | Result |\n|---|---|")
    out.append(f"| parity batched==sequential | "
               f"{parity.get('batched_eq_sequential', '?')} |")
    out.append(f"| parity fused==eager | "
               f"{parity.get('fused_eq_eager', '?')} |")
    out.append(f"| step tail p50 / p99 / p99.9 (ms) | "
               f"{tail.get('p50', '?')} / {tail.get('p99', '?')} / "
               f"{tail.get('p99_9', '?')} |")
    if lat:
        out.append(
            f"| lat cell ({lat.get('nbytes', '?')} B, window "
            f"{_fmt_bytes(int(lat.get('window_bytes', 0) or 0))}) | "
            f"`{lat.get('key', '?')}` predicted "
            f"{lat.get('predicted_lat_us', '?')} us vs hand "
            f"{lat.get('predicted_hand_us', '?')} us; measured "
            f"(memcpy mesh, unvarnished) {lat.get('measured_lat_us', '?')}"
            f" us vs register-0 {lat.get('measured_reg0_us', '?')} us "
            f"({lat.get('reg0_algorithm', '?')}) |")
    out.append(f"| shaped-WAN soak p50 / p99 / p99.9 (ms/step) | "
               f"{wan.get('p50', '?')} / {wan.get('p99', '?')} / "
               f"{wan.get('p99_9', '?')} (p99 ceiling "
               f"{d.get('wan_p99_ceiling_s', '?')} s) |")
    out.append(f"| gate verdict | "
               f"{'FAIL: ' + '; '.join(fails) if fails else 'pass'} |")
    out.append("")
    out.append("The lat-cell measured column is the dispatch-structure "
               "cost on the memcpy-wire mesh (no per-hop alpha there); "
               "the selection win is gated on the calibrated-link "
               "prediction. See docs/serving.md.\n")


def section_tenant(out: list[str]) -> None:
    """The multi-tenant scheduler soak (`bench.py --tenant-gate`
    verdict): small-tenant tail under a saturating bulk tenant, the
    certification counters, WFQ share, and noisy-neighbor blame.
    CPU-emulator numbers — the scheduler's own seams, not hardware."""
    p = LOG / "tenant_gate.json"
    out.append("## Multi-tenant scheduler — certified concurrent soak "
               "(`tenant_gate.json`)\n")
    if not p.exists():
        out.append("*absent — no tenant-gate run committed*\n")
        return
    try:
        d = json.loads(p.read_text())
    except ValueError:
        out.append("*unreadable*\n")
        return
    stats = d.get("stats", {})
    worst = d.get("worst", {})
    band = d.get("band", {})
    bulk = d.get("bulk", {})
    wfq = d.get("wfq", {})
    fails = d.get("fails", [])
    out.append(
        f"**Headline:** worst small-tenant p99 "
        f"{worst.get('p99_ms', '?')} ms = **{d.get('value', '?')}x** "
        f"its solo baseline ({d.get('small_p99_solo_ms', '?')} ms) "
        f"while the bulk tenant moved "
        f"{_fmt_bytes(int(bulk.get('wire_bytes', 0) or 0))} of "
        f"ring-wire traffic — band {worst.get('band_ms', '?')} ms "
        f"(solo x {band.get('p99_band', '?')} + "
        f"{band.get('hol_chunks', '?')} head-of-line chunks at "
        f"{band.get('bulk_chunk_p50_ms', '?')} ms). Platform: "
        f"{d.get('platform', '?')} — functional regime, not a "
        "hardware claim.\n")
    out.append("| Lane | Result |\n|---|---|")
    out.append(f"| dispatches (soak {d.get('soak_s', '?')} s) | "
               f"{stats.get('dispatches', '?')} total, "
               f"{stats.get('concurrent_dispatches', '?')} concurrent,"
               f" max {stats.get('max_inflight', '?')} in flight |")
    out.append(f"| certification | "
               f"{stats.get('certified_concurrent', '?')} certified / "
               f"{stats.get('uncertified_concurrent', '?')} "
               f"uncertified concurrent; "
               f"{stats.get('serialized_admissions', '?')} "
               f"serial-fallback admissions |")
    out.append(f"| bulk tenant | {bulk.get('chunks', '?')} chunks x "
               f"{_fmt_bytes(int(bulk.get('chunk_elems', 0) or 0) * 4)}"
               f" payload = "
               f"{_fmt_bytes(int(bulk.get('wire_bytes', 0) or 0))} "
               f"wire (budget "
               f"{_fmt_bytes(int(bulk.get('wire_budget', 0) or 0))}) |")
    out.append(f"| WFQ 4:1 first-10 share | "
               f"{wfq.get('first10_heavy_share', '?')} "
               f"(want {wfq.get('want', '?')} +- "
               f"{wfq.get('tol', '?')}) |")
    noisy = d.get("noisy_neighbors") or []
    blamed = [f"{r.get('tenant')}<-{r.get('noisy_neighbor')}"
              for r in noisy if r.get("noisy_neighbor")]
    out.append(f"| SLO misses / noisy neighbors | "
               f"{sum((d.get('slo_misses') or {}).values())} misses; "
               f"{', '.join(blamed) if blamed else 'none blamed'} |")
    out.append(f"| gate verdict | "
               f"{'FAIL: ' + '; '.join(fails) if fails else 'pass'} |")
    out.append("")
    out.append("Every concurrent admission carries a group certificate "
               "id; an uncertifiable pair queues in serial-fallback "
               "mode (counted above), never silently dropped. See "
               "docs/scheduler.md.\n")


def section_rt_stats(out: list[str]) -> None:
    """Sequencer counter evidence (tools/rt_stats_sweep.py) and what it
    established about the emulator's cost structure."""
    names = sorted(p.name for p in LOG.glob("rt_stats*.csv")) + \
        sorted(p.name for p in LOG.glob("rt_shape*.csv"))
    if not names:
        return
    out.append("## Native-runtime counter sweeps (`rt_stats*.csv`)\n")
    out.append("ACCL_RT_STATS pass/park/seek counters per "
               "(collective, size, world), with per-call seconds in the "
               "same row: " + ", ".join(f"`{n}`" for n in names) + ".\n")
    out.append(
        "What the counters established (r5 analysis, single-core CI "
        "host):\n\n"
        "- The transport itself streams at ~1.2-1.4 GB/s one-way at "
        ">= 64 KB segments (2-rank pingpong probe), but costs ~90 us "
        "per 4 KB segment — whole-chunk jumbo-segment streaming is "
        "mandatory for every ring/tree hop, and is now applied to all "
        "of them.\n"
        "- Per-hop wall cost is dominated by scheduler wakeup latency "
        "(~0.5 ms with 8 rank runtimes timesharing one core), so "
        "critical-path hop COUNT is what the clock sees at small "
        "payloads: recursive halving-doubling (2 log2 P hops) beats the "
        "ring (2(P-1)) below ~32 KB per hop saved, and loses above it "
        "because its larger per-hop messages overlap worse. The "
        "runtime's auto rule encodes exactly that measured crossover "
        "(forced-shape sweeps in `rt_shape_*.csv`).\n"
        "- At >= 1 MB the path is aggregate-copy-bound: an allreduce "
        "must move 2n(P-1) wire bytes across ranks vs bcast's n(P-1) "
        "— on a serialized-memory-bandwidth host allreduce therefore "
        "costs >= 2x bcast at equal payload BY VOLUME, independent of "
        "algorithm. The r4 target 'allreduce >= bcast at >= 1 MB' is "
        "structurally unreachable on this host; parity per moved byte "
        "is (allreduce moves 2x the bytes in ~2.3x the time at 1 MB / "
        "8w).\n"
        "- The 200 us park backstop itself burned the core (5k spurious "
        "wakeups/s across parked sequencers); the event-counter "
        "predicate does the real waking, so the backstop is now 2 ms.\n")


def section_timing(out: list[str]) -> None:
    p = LOG / "timing_model.json"
    out.append("## Timing model (cclo_sim slot)\n")
    if not p.exists():
        out.append("*absent*\n")
        return
    tm = json.loads(p.read_text())
    fit = tm.get("fit", {})
    percoll = tm.get("link_per_collective")
    if percoll:
        out.append(
            f"Per-collective alpha-beta fits from `{tm.get('source', '?')}` "
            f"over {fit.get('rows', '?')} rows, on the "
            f"{tm.get('cost_shape', 'aggregate')} cost shape:\n")
        for name, lk in percoll.items():
            out.append(f"- **{name}** ({lk.get('rows', '?')} rows): alpha "
                       f"{lk.get('alpha_us', float('nan')):.1f} us, beta "
                       f"{lk.get('beta_gbps', float('nan')):.3f} GB/s")
        hold = fit.get("median_holdout_pred_over_meas")
        out.append(
            f"\nMedian predicted/measured "
            f"{fit.get('median_pred_over_meas', float('nan')):.2f}; "
            f"{fit.get('holdout', 'holdout')} median "
            + (f"{hold:.2f}" if hold else "n/a")
            + f" across worlds {fit.get('worlds', '?')}.\n")
    else:
        link = tm.get("link", {})
        out.append(
            f"Alpha-beta link fit from `{tm.get('source', '?')}`: "
            f"alpha {link.get('alpha_us', float('nan')):.1f} us, "
            f"beta {link.get('beta_gbps', float('nan')):.2f} GB/s over "
            f"{fit.get('rows', '?')} rows "
            f"(median predicted/measured "
            f"{fit.get('median_pred_over_meas', float('nan')):.2f}).\n")
    cross = tm.get("tuning_crossovers")
    if cross:
        out.append("Tuning-register crossovers reproduced as performance "
                   "switches (reference defaults: bcast flat <= 3 ranks, "
                   "reduce flat <= 4 ranks / <= 32 KB):\n")
        for k, v in cross.items():
            v_s = _fmt_bytes(int(v)) if "bytes" in k else v
            out.append(f"- {k}: {v_s}")
        out.append("")
    for key, title in (("local_poe_tier", "Local-POE tier"),
                       ("udp_poe_tier", "Datagram-POE tier")):
        lp = tm.get(key)
        if not lp:
            continue
        links = ", ".join(
            f"{name} alpha {lk['alpha_us']:.1f} us / beta "
            f"{lk['beta_gbps']:.2f} GB/s"
            for name, lk in lp.get("link_per_collective", {}).items())
        med = lp.get("fit", {}).get("median_pred_over_meas")
        out.append(
            f"**{title}** (from `{lp.get('source', '?')}`): {links}"
            f" — median predicted/measured "
            + (f"{med:.2f}" if med else "n/a")
            + f" over {lp.get('fit', {}).get('rows', '?')} rows.\n")
    tpu = tm.get("tpu_tier")
    if tpu:
        beta = tpu.get("dispatch_beta_gbps")
        hbm = tpu.get("hbm_stream_gbps")
        out.append(
            f"**TPU tier** (from `{tpu.get('source', '?')}`): dispatch "
            f"alpha {tpu.get('dispatch_alpha_us', float('nan')):.0f} us"
            + (f", datapath beta {beta:.1f} GB/s" if beta
               else " (dispatch-bound: datapath beta unresolved)")
            + (f", HBM stream {hbm:.0f} GB/s" if hbm else "")
            + "; ICI beta unmeasured (single-chip tunnel).\n")


def main() -> int:
    out: list[str] = ["# accl-tpu benchmark report\n"]
    out.append("Generated by tools/report_bench.py from committed "
               "artifacts in accl_log/. Reference roles: "
               "parse_bench_results.py + Coyote plot.py.\n")
    section_trajectory(out)
    section_tpu(out)
    section_flagship(out)
    section_serving(out)
    section_tenant(out)
    section_emulator(out)
    section_rt_stats(out)
    section_timing(out)
    text = "\n".join(out) + "\n"
    (LOG / "REPORT.md").write_text(text)
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
