"""TPU tunnel liveness probe loop.

The tunneled TPU backend can wedge (a stale relay lease hangs
``jax.devices()`` forever — see bench.py's watchdog). One wedge must not
forfeit a whole round of hardware measurements, so this harness re-probes
at intervals and leaves a machine-readable trail:

  accl_log/tpu_probe.log   timestamped status line per attempt
  accl_log/TPU_ALIVE       sentinel written the moment a probe succeeds
                           (content: ISO timestamp of the successful probe)

Run detached: ``nohup python tools/tpu_probe_loop.py &``. Exits after the
first success (the caller then launches the real hardware suite/bench) or
after --max-hours.

Each probe runs ``jax.devices()`` in a SUBPROCESS with a hard timeout, so
the loop itself can never hang; the child inherits the platform plugin via
sitecustomize. Mirrors __graft_entry__._tpu_reachable.
"""

import argparse
import datetime
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # for __graft_entry__._probe_tpu
LOG = REPO / "accl_log" / "tpu_probe.log"
SENTINEL = REPO / "accl_log" / "TPU_ALIVE"


def _now() -> str:
    return datetime.datetime.now().isoformat(timespec="seconds")


def log(msg: str) -> None:
    LOG.parent.mkdir(exist_ok=True)
    with open(LOG, "a") as f:
        f.write(f"{_now()} {msg}\n")


def probe(timeout_s: int) -> bool:
    from __graft_entry__ import _probe_tpu  # the one shared watchdog

    ok, detail = _probe_tpu(timeout_s)
    log(("ALIVE " if ok else "") + detail.replace("\n", " | "))
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval-min", type=float, default=20.0)
    ap.add_argument("--timeout-s", type=int, default=150)
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    # a sentinel from a PREVIOUS run must not make a caller launch the
    # hardware suite against a currently-wedged tunnel
    SENTINEL.unlink(missing_ok=True)
    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        log(f"attempt {attempt}")
        if probe(args.timeout_s):
            SENTINEL.write_text(_now() + "\n")
            log("sentinel written; exiting")
            return 0
        time.sleep(args.interval_min * 60)
    log("max-hours reached without a live tunnel")
    return 1


if __name__ == "__main__":
    sys.exit(main())
