"""TPU tunnel liveness probe loop.

The tunneled TPU backend can wedge (a stale relay lease hangs
``jax.devices()`` forever — see bench.py's watchdog). One wedge must not
forfeit a whole round of hardware measurements, so this harness re-probes
at intervals and leaves a machine-readable trail:

  accl_log/tpu_probe.log   timestamped status line per attempt
  accl_log/TPU_ALIVE       sentinel written the moment a probe succeeds
                           (content: ISO timestamp of the successful probe)

Run detached: ``nohup python tools/tpu_probe_loop.py &``. Exits after the
first success (the caller then launches the real hardware suite/bench) or
after --max-hours.

Each probe runs ``jax.devices()`` in a SUBPROCESS with a hard timeout, so
the loop itself can never hang; the child inherits the platform plugin via
sitecustomize. Mirrors __graft_entry__._tpu_reachable.
"""

import argparse
import datetime
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "accl_log" / "tpu_probe.log"
SENTINEL = REPO / "accl_log" / "TPU_ALIVE"


def _now() -> str:
    return datetime.datetime.now().isoformat(timespec="seconds")


def log(msg: str) -> None:
    LOG.parent.mkdir(exist_ok=True)
    with open(LOG, "a") as f:
        f.write(f"{_now()} {msg}\n")


def probe(timeout_s: int) -> bool:
    import tempfile

    # stderr to a FILE, not a pipe: a grandchild of the platform plugin
    # can hold a pipe open past the kill and block the drain forever
    with tempfile.TemporaryFile(mode="w+b") as errf:
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices())"],
                timeout=timeout_s, stdout=subprocess.PIPE, stderr=errf)
            if r.returncode == 0:
                log(f"ALIVE {r.stdout.decode().strip()}")
                return True
            errf.seek(0)
            tail = errf.read()[-300:].decode(errors="replace")
            log(f"probe rc={r.returncode}: {tail!r}")
        except subprocess.TimeoutExpired:
            log(f"probe hung past {timeout_s}s (wedged tunnel)")
        except Exception as e:
            log(f"probe error: {e!r}")
    return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval-min", type=float, default=20.0)
    ap.add_argument("--timeout-s", type=int, default=150)
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        log(f"attempt {attempt}")
        if probe(args.timeout_s):
            SENTINEL.write_text(_now() + "\n")
            log("sentinel written; exiting")
            return 0
        time.sleep(args.interval_min * 60)
    log("max-hours reached without a live tunnel")
    return 1


if __name__ == "__main__":
    sys.exit(main())
