"""TPU tunnel liveness probe loop.

The tunneled TPU backend can wedge (a stale relay lease hangs
``jax.devices()`` forever — see bench.py's watchdog). One wedge must not
forfeit a whole round of hardware measurements, so this harness re-probes
at intervals and leaves a machine-readable trail:

  accl_log/tpu_probe.log   timestamped status line per attempt
  accl_log/TPU_ALIVE       sentinel written the moment a probe succeeds
                           (content: ISO timestamp of the successful probe)

Run detached: ``nohup python tools/tpu_probe_loop.py &``. On the first
success it writes the sentinel, then (with --run-on-alive, the default)
immediately runs the hardware payload serially — the Mosaic-compile HW
suite and the on-chip bench — so a recovery at ANY hour produces
committed-ready artifacts without an operator in the loop:

  accl_log/hw_suite.log    ACCL_TPU_HW=1 pytest tests/test_tpu_hw.py
  accl_log/bench_tpu.log   python bench.py (writes accl_log/profile.csv)

Exits after the payload (or after --max-hours without a live tunnel).

Each probe runs ``jax.devices()`` in a SUBPROCESS with a hard timeout, so
the loop itself can never hang; the child inherits the platform plugin via
sitecustomize. Mirrors __graft_entry__._tpu_reachable.
"""

import argparse
import datetime
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # for __graft_entry__._probe_tpu
# round-stamped (--stamp r05) so the long-running loop never dirties a
# committed log between snapshot and round end; LOG is rebound in main()
LOG = REPO / "accl_log" / "tpu_probe.log"
SENTINEL = REPO / "accl_log" / "TPU_ALIVE"
STAMP = ""


def _now() -> str:
    return datetime.datetime.now().isoformat(timespec="seconds")


def log(msg: str) -> None:
    LOG.parent.mkdir(exist_ok=True)
    with open(LOG, "a") as f:
        f.write(f"{_now()} {msg}\n")


def probe(timeout_s: int) -> bool:
    from __graft_entry__ import _probe_tpu  # the one shared watchdog

    ok, detail = _probe_tpu(timeout_s)
    log(("ALIVE " if ok else "") + detail.replace("\n", " | "))
    return ok


def run_hw_payload() -> None:
    """Serially run the hardware suite and the on-chip bench with generous
    timeouts (first compiles are remote and slow); each to its own log.
    Serial on purpose: concurrent heavy jobs saturate the box and a killed
    TPU-attached process can re-wedge the tunnel."""
    import subprocess

    jobs = [
        # barrier cross-check first: cheap, and it validates the timing
        # methodology every later lane depends on
        ("fetch_barrier",
         ["python", str(REPO / "tools" / "fetch_barrier_check.py")],
         {}, 1200),
        ("hw_suite", ["python", "-m", "pytest", "tests/test_tpu_hw.py",
                      "-v", "-x"], {"ACCL_TPU_HW": "1"}, 3600),
        # full mode: 8-collective sweep (w1 lanes up to 256 MB so the
        # datapath beta resolves) + Pallas tile sweep + flagship MFU +
        # decode — each (op, size) costs a remote compile, hence the
        # generous timeout
        ("bench_tpu", ["python", str(REPO / "bench.py")],
         {"ACCL_BENCH_FULL": "1"}, 7200),
        # recalibrate the timing model's TPU tier from the fresh profile
        ("timing_model",
         ["python", str(REPO / "tools" / "timing_model.py")], {}, 600),
        ("report", ["python", str(REPO / "tools" / "report_bench.py")],
         {}, 600),
    ]
    import os

    for name, cmd, extra_env, tmo in jobs:
        logp = REPO / "accl_log" / f"{name}{STAMP}.log"
        env = dict(os.environ)
        env.update(extra_env)
        if STAMP:
            env["ACCL_BENCH_STAMP"] = STAMP.lstrip("_")
        log(f"payload {name}: {' '.join(cmd)}")
        try:
            with open(logp, "w") as f:
                r = subprocess.run(cmd, cwd=REPO, env=env, stdout=f,
                                   stderr=subprocess.STDOUT, timeout=tmo)
            log(f"payload {name}: rc={r.returncode} -> {logp.name}")
        except subprocess.TimeoutExpired:
            log(f"payload {name}: TIMEOUT after {tmo}s -> {logp.name}")
        except Exception as e:
            log(f"payload {name}: error {e!r}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval-min", type=float, default=20.0)
    ap.add_argument("--timeout-s", type=int, default=150)
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--no-run-on-alive", action="store_true",
                    help="only write the sentinel; skip the HW payload")
    ap.add_argument("--stamp", default="",
                    help="round stamp (e.g. r05): suffixes the probe log "
                         "and payload LOGS so the always-running loop "
                         "itself never dirties committed logs. Payload "
                         "jobs still write the canonical accl_log/ "
                         "artifacts (profile.csv, timing_model.json, "
                         "REPORT.md) — those are the round's evidence "
                         "and get committed when they appear")
    ap.add_argument("--keep-probing", action="store_true",
                    help="after a successful payload, keep probing (and "
                         "re-run the payload at most once more) until "
                         "--max-hours — a second recovery window should "
                         "not be wasted if the first payload ran on "
                         "stale code")
    args = ap.parse_args()
    global LOG, STAMP
    if args.stamp:
        STAMP = f"_{args.stamp}"
        LOG = REPO / "accl_log" / f"tpu_probe{STAMP}.log"

    # a sentinel from a PREVIOUS run must not make a caller launch the
    # hardware suite against a currently-wedged tunnel
    SENTINEL.unlink(missing_ok=True)
    deadline = time.time() + args.max_hours * 3600
    attempt = 0
    payload_runs = 0
    while time.time() < deadline:
        attempt += 1
        log(f"attempt {attempt}")
        if probe(args.timeout_s):
            SENTINEL.write_text(_now() + "\n")
            log("sentinel written")
            if args.no_run_on_alive:
                log("exiting (sentinel only)")
                return 0
            run_hw_payload()
            payload_runs += 1
            if not args.keep_probing or payload_runs >= 2:
                log("exiting")
                return 0
            log("keep-probing: payload done, watching for a later window")
        time.sleep(args.interval_min * 60)
    log("max-hours reached without a live tunnel"
        if payload_runs == 0 else "max-hours reached")
    return 0 if payload_runs else 1


if __name__ == "__main__":
    sys.exit(main())
