"""Multi-process DCN backend driver: one OS process per "host".

The multi-host analog of tools/run_emulator.py (reference: the Coyote
run scripts, test/host/Coyote/run_scripts/run.sh, which mpirun one driver
process per U55C host). Each process joins the jax.distributed
coordinator, builds the (dcn, ici) mesh through DCNDevice, and drives
facade-level collectives whose cross-process hops ride the DCN tier.

Usage (2 processes x 4 virtual CPU devices):
    python tools/run_dcn.py --procs 2 --proc-id 0 --port 9911 &
    python tools/run_dcn.py --procs 2 --proc-id 1 --port 9911
Prints one "RANKS ... OK" line per process on success (exit 0).
"""

from __future__ import annotations

import argparse
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, required=True)
    ap.add_argument("--proc-id", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=4)
    ap.add_argument("--count", type=int, default=96)
    ap.add_argument("--subset-hosts", type=int, default=0,
                    help="also run an allreduce on a sub-communicator of "
                         "the first K hosts (0 = skip)")
    args = ap.parse_args()

    import numpy as np

    from accl_tpu.accl import ACCL
    from accl_tpu.constants import ReduceFunction
    from accl_tpu.device.dcn_device import DCNDevice

    dev = DCNDevice(
        num_processes=args.procs,
        process_id=args.proc_id,
        coordinator_address=f"127.0.0.1:{args.port}",
        local_device_count=args.local_devices,
        platform="cpu",
    )
    a = ACCL(device=dev)
    world, n = a.world, args.count
    rows = dev.local_rows()
    rng = np.random.default_rng(17)  # same data on every process
    x = rng.standard_normal((world, n)).astype(np.float32)

    def stage(name):
        print(f"[p{args.proc_id}] {name}", flush=True)

    # hierarchical allreduce: DCN carries 1/inner of the payload
    stage("allreduce")
    sb, rb = a.create_buffer(n, data=x), a.create_buffer(n)
    a.allreduce(sb, rb, n, ReduceFunction.SUM)
    for r in rows:
        np.testing.assert_allclose(rb.host[r], x.sum(0), rtol=1e-4, atol=1e-4)

    # hierarchical bcast from a rank on the last process (multi-controller
    # SPMD: every process must issue the IDENTICAL program, so the root is
    # the same global rank everywhere)
    stage("bcast")
    root = world - 1
    bb = a.create_buffer(n, data=x)
    a.bcast(bb, n, root=root)
    for r in rows:
        np.testing.assert_allclose(bb.host[r], x[root], rtol=0)

    # hierarchical allgather (process-major chunk order)
    stage("allgather")
    gs, gb = a.create_buffer(n, data=x), a.create_buffer(n * world)
    a.allgather(gs, gb, n)
    for r in rows:
        np.testing.assert_allclose(gb.host[r], x.reshape(-1), rtol=0)

    # flat combined-axis fallback (alltoall) + cross-process p2p
    stage("alltoall")
    ts = a.create_buffer(world * 8, data=x[:, : world * 8])
    tr = a.create_buffer(world * 8)
    a.alltoall(ts, tr, 8)
    exp = x[:, : world * 8].reshape(world, world, 8).transpose(1, 0, 2)
    for r in rows:
        np.testing.assert_allclose(tr.host[r], exp[r].reshape(-1), rtol=0)

    stage("p2p")
    src, dst = 1, world - 1  # crosses the process boundary
    a.send(sb, 16, src=src, dst=dst, tag=5)
    pv = a.create_buffer(16)
    a.recv(pv, 16, src=src, dst=dst, tag=5)
    if dst in rows:
        np.testing.assert_allclose(pv.host[dst], x[src, :16], rtol=0)

    # outer-aligned sub-communicator: host 0's whole inner group. Every
    # process issues the same call; non-member hosts no-op (MPI
    # semantics), member hosts run the flat ICI-only path.
    stage("subcomm")
    local = world // args.procs
    host0 = a.split(list(range(local)))
    cb, cr = a.create_buffer(24, data=x[:, :24]), a.create_buffer(24)
    a.allreduce(cb, cr, 24, ReduceFunction.SUM, comm=host0)
    if args.proc_id == 0:
        for r in rows:
            np.testing.assert_allclose(cr.host[r], x[:local, :24].sum(0),
                                       rtol=1e-4, atol=1e-4)
    else:
        for r in rows:
            np.testing.assert_allclose(cr.host[r], 0.0)

    if args.subset_hosts:
        # cross-host sub-communicator: first K whole hosts. Member hosts
        # run a hierarchical collective on the (K, local) sub-mesh; the
        # rest no-op the same facade call.
        stage(f"subset-{args.subset_hosts}-hosts")
        k = args.subset_hosts
        grp = a.split(list(range(k * local)))
        kb, kr = a.create_buffer(16, data=x[:, :16]), a.create_buffer(16)
        a.allreduce(kb, kr, 16, ReduceFunction.SUM, comm=grp)
        if args.proc_id < k:
            for r in rows:
                np.testing.assert_allclose(
                    kr.host[r], x[: k * local, :16].sum(0),
                    rtol=1e-4, atol=1e-4)
        else:
            for r in rows:
                np.testing.assert_allclose(kr.host[r], 0.0)

    stage("barrier")
    a.barrier()
    print(f"RANKS {rows} proc {args.proc_id}/{args.procs} OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
