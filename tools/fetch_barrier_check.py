#!/usr/bin/env python3
"""Validate bench.py's `_fetch` execution barrier against a
checksum-dependent one (VERDICT r4 task: the headline TPU numbers must
not rest on the unverified platform claim that `block_until_ready`
returns early and a 4-element fetch suffices).

Method: time the same compiled K-deep combine loop three ways —

  fetch4      np.asarray(out.ravel()[:4])      (bench.py's barrier)
  checksum    on-device full sum over the WHOLE result, scalar pulled
  sum_tiny    the same checksum program over a 4-element array, timing
              the checksum machinery itself (its dispatch overhead)

If fetch4 were NOT a full barrier, its timings would undercut checksum
by the un-waited tail of the K-loop — which grows linearly in K. So the
check compares (checksum - sum_tiny_overhead) against fetch4 at two K
depths: agreement within the relay jitter at both depths means the
4-element read already orders after the whole computation.

Writes accl_log/fetch_barrier<suffix>.csv (suffix _cpu off-TPU, the
round stamp from ACCL_BENCH_STAMP appended) and prints a PASS/FAIL
verdict line. Run on CPU at commit time; the probe-loop payload re-runs
it on silicon in the recovery window.
"""

import csv
import os
import pathlib
import sys
import time

import numpy as np

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from bench import _fetch, _fetch_checksum  # noqa: E402


def time_barrier(fn, args, barrier, reps=5):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        barrier(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    nbytes = 1 << 28 if on_tpu else 1 << 24
    n = nbytes // 4
    a = jax.device_put(np.random.default_rng(0)
                       .standard_normal(n).astype(np.float32))
    b = jax.device_put(np.random.default_rng(1)
                       .standard_normal(n).astype(np.float32))
    run = jax.jit(
        lambda x, y, k: lax.fori_loop(0, k, lambda i, c: jnp.add(c, y), x))
    tiny = jax.device_put(np.zeros(4, np.float32))
    tiny_id = jax.jit(lambda x: x + 0)

    # warm every compiled program + both barrier paths
    _fetch(run(a, b, jnp.int32(2)))
    _fetch_checksum(run(a, b, jnp.int32(2)))
    _fetch_checksum(tiny_id(tiny))

    # the checksum program's own cost, measured where the payload is 4
    # elements (pure dispatch + scalar pull)
    overhead = time_barrier(tiny_id, (tiny,), _fetch_checksum)

    rows = []
    verdict = "PASS"
    for k in (4, 32):
        kk = jnp.int32(k)
        t_fetch = time_barrier(run, (a, b, kk), _fetch)
        t_sum = time_barrier(run, (a, b, kk), _fetch_checksum)
        # jitter scale: spread of repeated fetch4 runs at this K
        times = [time_barrier(run, (a, b, kk), _fetch, reps=1)
                 for _ in range(5)]
        jitter = max(times) - min(times)
        excess = t_sum - overhead - t_fetch
        # fail only when checksum exceeds fetch4 by more than the
        # observed jitter AND by a meaningful fraction of the loop time
        ok = excess <= max(4 * jitter, 0.25 * t_fetch)
        if not ok:
            verdict = "FAIL"
        rows.append((k, nbytes, t_fetch, t_sum, overhead, jitter,
                     "ok" if ok else "EXCESS"))
        print(f"  K={k:3d} fetch4={t_fetch*1e3:9.3f} ms  "
              f"checksum={t_sum*1e3:9.3f} ms  "
              f"overhead={overhead*1e3:7.3f} ms  "
              f"jitter={jitter*1e3:7.3f} ms  {'ok' if ok else 'EXCESS'}",
              file=sys.stderr)

    stamp = os.environ.get("ACCL_BENCH_STAMP", "")
    suffix = ("" if on_tpu else "_cpu") + (f"_{stamp}" if stamp else "")
    out = REPO / "accl_log" / f"fetch_barrier{suffix}.csv"
    out.parent.mkdir(exist_ok=True)
    with open(out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["K", "Bytes", "Fetch4Sec", "ChecksumSec",
                    "ChecksumOverheadSec", "JitterSec", "Status"])
        w.writerows(rows)
    plat = "tpu" if on_tpu else "cpu"
    print(f"fetch_barrier_check [{plat}]: {verdict} -> {out.name}")
    return 0 if verdict == "PASS" else 1


if __name__ == "__main__":
    sys.exit(main())
