#!/usr/bin/env python3
"""Launch N native emulator ranks as separate OS processes.

The analog of the reference's emulator launcher
(test/model/emulator/run.py:45-58: spawn N cclo_emu processes wired by
port). Each process brings up one EmuRank and executes a demo collective
round (or a user script via --script module:function, called as
fn(rank, rank_idx, world)).

Usage:
  python tools/run_emulator.py -n 4                    # demo allreduce
  python tools/run_emulator.py -n 4 --script mymod:fn  # custom per-rank fn
"""

import argparse
import importlib
import multiprocessing as mp
import pathlib
import sys

import numpy as np

REPO = pathlib.Path(__file__).resolve().parents[1]


def _demo(rank, idx, world):
    from accl_tpu import ReduceFunction

    n = 4096
    x = np.full(n, float(idx + 1), np.float32)
    out = np.zeros(n, np.float32)
    rank.allreduce(x, out, n, ReduceFunction.SUM)
    expected = world * (world + 1) / 2
    ok = np.allclose(out, expected)
    print(f"[rank {idx}] allreduce({n}) -> {out[0]:.1f} "
          f"(expect {expected:.1f}) {'OK' if ok else 'MISMATCH'}")
    rank.barrier()
    return ok


def worker(world, idx, ports, script, q, transport="tcp"):
    sys.path.insert(0, str(REPO))
    from accl_tpu.device.emu_device import EmuRank

    rank = EmuRank(world, idx, ports, transport=transport)
    try:
        if script:
            mod, fn = script.split(":")
            f = getattr(importlib.import_module(mod), fn)
        else:
            f = _demo
        q.put((idx, bool(f(rank, idx, world))))
    except Exception as e:  # pragma: no cover
        q.put((idx, f"error: {e}"))
    finally:
        rank.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--world", type=int, default=2)
    ap.add_argument("--script", default=None,
                    help="module:function run per rank as fn(rank, idx, world)")
    ap.add_argument("--transport", choices=("tcp", "udp"), default="tcp",
                    help="session TCP mesh or sessionless datagram POE")
    args = ap.parse_args()

    sys.path.insert(0, str(REPO))
    from accl_tpu.device.emu_device import free_ports

    ports = free_ports(args.world)
    q = mp.Queue()
    procs = [
        mp.Process(target=worker,
                   args=(args.world, i, ports, args.script, q,
                         args.transport),
                   daemon=True)
        for i in range(args.world)
    ]
    try:
        for p in procs:
            p.start()
        results = {}
        for _ in range(args.world):
            try:
                k, v = q.get(timeout=120)
            except Exception:
                break  # a rank died before reporting
            results[k] = v
        for p in procs:
            p.join(timeout=30)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
    bad = {k: v for k, v in results.items() if v is not True}
    missing = set(range(args.world)) - set(results)
    if bad or missing:
        print(f"FAILED ranks: {bad} missing: {sorted(missing)}",
              file=sys.stderr)
        sys.exit(1)
    print(f"all {args.world} ranks OK")


if __name__ == "__main__":
    main()
