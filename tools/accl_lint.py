#!/usr/bin/env python
"""accl_lint: replay recorded descriptor batches through the static
analyzer (accl_tpu/analysis/, docs/lint.md).

Three modes, combinable:

  --corpus [DIR]   replay every *.json fixture under DIR (default
                   tools/lint_corpus/): known-bad batches must be
                   rejected with their expected diagnostic codes,
                   known-good batches must come back clean
  --schedules      abstractly interpret every shipping schedule family
                   in sequencer/schedules.py (both protocol regimes,
                   several worlds/roots) and require zero diagnostics
  --deep           force the deep tier everywhere: fixtures run the
                   exhaustive-interleaving model checker (ACCL205-207)
                   even without "deep": true, and --schedules
                   model-checks every config's hop programs over all
                   match orders (budgeted; truncation fails the gate)
  --semantic       semantic certification (ACCL501-504): --schedules
                   additionally proves every config's contribution sets
                   equal its declared collective (strict: a schedule
                   the certifier cannot lift FAILS the gate), and the
                   corpus replay enforces "expect_semantic" exactly
  --sample N       deterministically subsample the --schedules sweep
                   to ~N configs (the CI slice for the deep tier)
  --interference   cross-program pair sweep (ACCL601-604): pairwise-
                   certify concurrent footprints over every shipped
                   schedule family (disjoint arenas must certify clean
                   via summaries ALONE — zero escalations), adversarial
                   overlap/slot/steal/unliftable rows must reject with
                   their exact codes, and the recorded MoE / decode /
                   train-step program pairs must certify clean or
                   reject with a stable ACCL6xx (never ACCL604)
  FILE...          lint individual fixture files

Exit status is 0 only when every expectation holds — the CI lint job
runs `accl_lint.py --corpus --schedules` (default tier),
`accl_lint.py --interference --corpus`, and
`accl_lint.py --deep --corpus --schedules --sample N` as gates.

Fixture schema (JSON):
  kind "sequence":       "steps" (descriptor dicts: op/count/dtype/
                         addr_0/addr_1/addr_2/root/function/tag/comm)
                         or "words" (the batched 15-word call stream),
                         plus optional "world", "deep",
                         "use_pallas_ring", "overlap", "buffer_widths"
  kind "rank_programs":  "programs": per-rank event lists
                         ({kind: send|recv|coll, peer, tag, count,
                         comm, op} — peer "any" is the any-source
                         wildcard), optional "blocking_sends", "deep"
                         (run the interleaving checker over the
                         programs), "budget_states"
  kind "slots":          "num_slots", "instances" [[step, seg, slot]],
                         "deps" [[from, to]]
  kind "hopdag":         "dag" (analysis.hopdag.to_json form) plus
                         "collective" ({op, count, root, function}) —
                         the protocol passes run over the DAG's hops
                         (these must satisfy "expect", [] for the
                         bad-semantic fixtures: the point is that the
                         linter/model checker ALONE pass them) and the
                         semantic certifier checks the DAG against the
                         declared collective ("expect_semantic")
  kind "concurrent":     "tenants": list of sub-fixtures (each of kind
                         "sequence" or "rank_programs", same schema as
                         above plus optional "title"/"world"/
                         "use_pallas_ring"/"overlap"/"persistent");
                         each tenant is lifted to its ProgramFootprint
                         and the set is pairwise-certified
                         (analysis/interference.py, ACCL601-604).
                         "expect" is enforced EXACTLY (set equality —
                         a cross-program fixture must reject with its
                         precise codes, no more, no less); optional
                         "expect_escalations" pins the product-
                         modelcheck escalation count (0 proves the
                         summary-only fast path)
  all kinds:             "expect": diagnostic codes that MUST surface
                         ([] = the batch must lint clean), "title";
                         "expect_semantic": ACCL5xx codes the semantic
                         certifier must emit, EXACTLY (set equality)
"""

import argparse
import json
import os
import pathlib
import sys

# the deep pass traces schedule bodies under jax's abstract evaluation;
# keep that off any real accelerator (and quiet) regardless of where
# the CLI runs — must happen before anything imports jax. The
# --interference model-pair sweep records real programs over an 8-way
# virtual mesh, so ask for the devices up front (a user-set XLA_FLAGS
# wins; the sweep adapts to whatever device count materializes).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from accl_tpu.constants import (  # noqa: E402
    DEFAULT_EAGER_RX_BUF_SIZE,
    DEFAULT_MAX_EAGER_SIZE,
    DEFAULT_MAX_RENDEZVOUS_SIZE,
    DataType,
    Operation,
    ReduceFunction,
    TAG_ANY,
    TuningParams,
    dtype_nbytes,
)
from accl_tpu.descriptor import CallOptions, SequenceDescriptor  # noqa: E402
from accl_tpu.analysis import (  # noqa: E402
    SequenceLinter,
    check_slots,
    simulate,
)
from accl_tpu.analysis.modelcheck import Budget  # noqa: E402
from accl_tpu.analysis.protocol import (  # noqa: E402
    ANY_SRC,
    Event,
    check_hops,
    rank_programs_from_hops,
    trace_schedule_hops,
)
from accl_tpu.analysis.slots import SlotInstance, SlotTimeline  # noqa: E402
from accl_tpu.analysis import hopdag as hopdag_mod  # noqa: E402
from accl_tpu.analysis import semantics as semantics_mod  # noqa: E402
from accl_tpu.sequencer.plan import select_algorithm  # noqa: E402

DEFAULT_CORPUS = pathlib.Path(__file__).resolve().parent / "lint_corpus"


def _step_from_dict(d: dict) -> CallOptions:
    from accl_tpu.constants import CompressionFlags

    op = Operation[d["op"]]
    fn = d.get("function", 0)
    if isinstance(fn, str):
        fn = int(ReduceFunction[fn])
    dt = d.get("dtype", "float32")
    data_type = DataType[dt] if isinstance(dt, str) else DataType(dt)
    # "compress": wire dtype of an ETH_COMPRESSED call (e.g. "int8" for
    # the blockwise-quantized lanes) — mirrors the facade's
    # compress_dtype resolution in _prepare
    cp = d.get("compress")
    compress_dtype = (DataType[cp] if isinstance(cp, str)
                      else DataType(cp)) if cp is not None else DataType.none
    comp_flags = (CompressionFlags.ETH_COMPRESSED
                  if compress_dtype not in (DataType.none, data_type)
                  else CompressionFlags.NO_COMPRESSION)
    return CallOptions(
        scenario=op,
        count=int(d.get("count", 0)),
        comm_addr=int(d.get("comm", 0)),
        root_src_dst=int(d.get("root", d.get("root_src_dst", 0))),
        function=int(fn),
        tag=int(d.get("tag", TAG_ANY)),
        addr_0=int(d.get("addr_0", 0)),
        addr_1=int(d.get("addr_1", 0)),
        addr_2=int(d.get("addr_2", 0)),
        data_type=data_type,
        compress_dtype=compress_dtype,
        compression_flags=comp_flags,
        # "live_ranks": the declared surviving-contributor set of a
        # degraded live-subset allreduce (the certifier's spec demands
        # exactly these ranks' contributions — docs/resilience.md)
        live_ranks=tuple(int(r) for r in d.get("live_ranks", ())),
    )


def _default_plan(opts: CallOptions, world: int):
    return select_algorithm(
        opts.scenario, opts.count, dtype_nbytes(opts.data_type), world,
        opts.compression_flags, opts.stream_flags,
        max_eager_size=DEFAULT_MAX_EAGER_SIZE,
        eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE,
        tuning=TuningParams.default(DEFAULT_MAX_RENDEZVOUS_SIZE),
        compress_dtype=opts.compress_dtype,
        live_ranks=opts.live_ranks,
    )


def _fixture_budget(fx: dict) -> Budget:
    if "budget_states" in fx:
        return Budget(max_states=int(fx["budget_states"]))
    return Budget()


def _programs_from_fixture(fx: dict) -> list:
    def peer_of(e: dict) -> int:
        p = e.get("peer", -1)
        return ANY_SRC if p in ("any", "ANY") else int(p)

    return [
        [Event(e["kind"], peer_of(e),
               int(e.get("tag", TAG_ANY)), int(e.get("count", 0)),
               int(e.get("comm", 0)), e.get("op", ""))
         for e in prog]
        for prog in fx["programs"]
    ]


def _tenant_footprint(t: dict, i: int, default_world: int):
    """Lift one "concurrent" sub-fixture to its ProgramFootprint —
    through the SAME extractors the device attaches at compile time, so
    the corpus replays exactly what certify_concurrent sees."""
    from accl_tpu.analysis.interference import (
        footprint_from_rank_programs, footprint_from_steps)

    kind = t.get("kind", "sequence")
    world = int(t.get("world", default_world))
    label = t.get("title", f"tenant{i}")
    if kind == "sequence":
        steps = [_step_from_dict(d) for d in t["steps"]]
        plans = tuple(_default_plan(o, world) for o in steps)
        return footprint_from_steps(
            steps, world,
            persistent=frozenset(int(a) for a in t.get("persistent", ())),
            use_pallas_ring=bool(t.get("use_pallas_ring", False)),
            pallas_ring_overlap=bool(t.get("overlap", True)),
            plans=plans, label=label)
    if kind == "rank_programs":
        return footprint_from_rank_programs(
            _programs_from_fixture(t), world, label=label)
    raise ValueError(f"unknown tenant kind {kind!r}")


def lint_fixture(fx: dict, deep: bool = False) -> list:
    """Run one fixture through the analyzer; returns Diagnostics.
    `deep=True` (the CLI's --deep) forces the exhaustive-interleaving
    tier even for fixtures that don't opt in with `"deep": true`."""
    kind = fx.get("kind", "sequence")
    world = int(fx.get("world", 4))
    if kind == "sequence":
        if "words" in fx:
            steps = list(
                SequenceDescriptor.from_words(list(fx["words"])).steps)
        else:
            steps = [_step_from_dict(d) for d in fx["steps"]]
        widths = None
        if "buffer_widths" in fx:
            widths = {int(k, 0) if isinstance(k, str) else int(k): int(v)
                      for k, v in fx["buffer_widths"].items()}
        linter = SequenceLinter(
            world,
            use_pallas_ring=bool(fx.get("use_pallas_ring", False)),
            pallas_ring_overlap=bool(fx.get("overlap", True)),
            deep=deep or bool(fx.get("deep", False)),
            budget=_fixture_budget(fx),
        )
        plans = [_default_plan(o, world) for o in steps]
        return linter.lint(steps, plans, buffer_widths=widths)
    if kind == "rank_programs":
        programs = _programs_from_fixture(fx)
        diags = simulate(programs,
                         blocking_sends=bool(fx.get("blocking_sends",
                                                    True)))
        if (deep or fx.get("deep", False)) and not diags:
            # deep tier: certify the chains over EVERY legal match
            # order, not just the canonical schedule simulate ran
            diags = SequenceLinter(
                world,
                budget=_fixture_budget(fx)).check_interleavings(programs)
        return diags
    if kind == "slots":
        timeline = SlotTimeline(
            int(fx["num_slots"]),
            [SlotInstance(*map(int, i)) for i in fx["instances"]],
            {(int(a), int(b)) for a, b in fx.get("deps", [])},
        )
        return check_slots(timeline)
    if kind == "hopdag":
        # raw hop-DAG fixtures: the protocol/model-check passes see the
        # DAG's hops as per-rank programs (for the bad-semantic corpus
        # these must come back CLEAN — the class only the semantic
        # certifier catches), then the certifier checks the DAG against
        # its declared collective
        dag = hopdag_mod.from_json(fx["dag"])
        programs = hopdag_mod.rank_programs(dag)
        diags = simulate(programs, blocking_sends=False)
        if (deep or fx.get("deep", False)) and not diags:
            diags = SequenceLinter(
                dag.world,
                budget=_fixture_budget(fx)).check_interleavings(programs)
        coll = fx.get("collective")
        if coll is not None:
            opts = _step_from_dict(coll)
            spec = semantics_mod.collective_spec(opts, dag.world)
            diags = list(diags) + semantics_mod.certify(
                dag, spec, opts.scenario.name)
        return diags
    if kind == "concurrent":
        from accl_tpu.analysis.interference import InterferenceCertifier

        # every tenant must certify ALONE first — cross-program
        # fixtures demonstrate defects only the pairwise tier sees, so
        # a tenant failing its own single-program passes is a broken
        # fixture, not an interference finding
        solo = []
        for i, t in enumerate(fx["tenants"]):
            solo += lint_fixture({"world": fx.get("world", 4), **t},
                                 deep=deep)
        if solo:
            return solo
        certifier = InterferenceCertifier(budget=_fixture_budget(fx))
        fps = [_tenant_footprint(t, i, int(fx.get("world", 4)))
               for i, t in enumerate(fx["tenants"])]
        diags = certifier.certify(fps)
        want_esc = fx.get("expect_escalations")
        if want_esc is not None and certifier.escalations != int(want_esc):
            raise AssertionError(
                f"expected {want_esc} product-modelcheck escalations, "
                f"certifier took {certifier.escalations} (the summary-"
                "only fast path is part of this fixture's contract)")
        return diags
    raise ValueError(f"unknown fixture kind {kind!r}")


def run_fixture_file(path: pathlib.Path,
                     deep: bool = False) -> tuple[bool, str]:
    fx = json.loads(path.read_text())
    diags = lint_fixture(fx, deep=deep)
    got = [d.code for d in diags]
    expect = fx.get("expect", [])
    expect_sem = fx.get("expect_semantic")
    if expect_sem is not None:
        # semantic expectations are EXACT (set equality on the ACCL5xx
        # codes): a bad-semantic fixture must be rejected with its
        # specific code, and the non-semantic passes must satisfy
        # "expect" — [] meaning the linter/model checker alone pass it
        got5 = sorted({c for c in got if c.startswith("ACCL5")})
        rest = [c for c in got if not c.startswith("ACCL5")]
        sem_ok = got5 == sorted(set(expect_sem))
        if expect:
            rest_ok = not [c for c in expect if c not in rest]
        else:
            rest_ok = not rest
        ok = sem_ok and rest_ok
        verdict = (f"semantic {got5 or ['clean']}"
                   + (f" + {sorted(set(rest))}" if rest else "")
                   if ok else
                   f"EXPECTED semantic {sorted(set(expect_sem))} got "
                   f"{got5} (other codes: {sorted(set(rest))})")
    elif fx.get("kind") == "concurrent":
        # cross-program fixtures are EXACT: the pairwise certifier must
        # emit precisely the expected code set — a fixture built to
        # reject ACCL602 surfacing a stray ACCL601 means the footprint
        # regions drifted, and that must fail the replay
        ok = sorted({c for c in got}) == sorted(set(expect))
        verdict = ((f"rejected with exactly {sorted(set(got))}"
                    if expect else "clean") if ok else
                   f"EXPECTED exactly {sorted(set(expect))} got "
                   f"{sorted(set(got))}")
    elif expect:
        missing = [c for c in expect if c not in got]
        ok = not missing
        verdict = (f"rejected with {sorted(set(got))}" if ok else
                   f"MISSED {missing} (got {sorted(set(got))})")
    else:
        ok = not diags
        verdict = "clean" if ok else f"UNEXPECTED {sorted(set(got))}"
    detail = "".join(f"\n      {d}" for d in diags) if not ok else ""
    return ok, f"{path.name:40s} {verdict}{detail}"


def run_corpus(corpus_dir: pathlib.Path, deep: bool = False) -> bool:
    files = sorted(corpus_dir.glob("*.json"))
    if not files:
        print(f"no fixtures under {corpus_dir}", file=sys.stderr)
        return False
    ok_all = True
    n_bad = n_good = 0
    for path in files:
        try:
            ok, line = run_fixture_file(path, deep=deep)
        except Exception as e:  # a crashing fixture is a failing fixture
            ok, line = False, f"{path.name:40s} ERROR {type(e).__name__}: {e}"
        ok_all &= ok
        fx_d = json.loads(path.read_text())
        is_bad = bool(fx_d.get("expect")) or bool(
            fx_d.get("expect_semantic"))
        n_bad += is_bad
        n_good += not is_bad
        print(("  ok  " if ok else " FAIL ") + line)
    print(f"corpus: {len(files)} fixtures "
          f"({n_bad} known-bad, {n_good} known-good)")
    return ok_all


def run_schedules(deep: bool = False, sample: int = 0,
                  semantic: bool = False) -> bool:
    """Interpret every shipping schedule family per rank and require it
    clean — the conformance half of the acceptance gate. `deep=True`
    additionally model-checks each config's hop programs over every
    legal match order (ACCL205-207; a truncated exploration FAILS the
    gate — the sweep must complete within budget, never silently
    partial). `semantic=True` additionally certifies every config's
    contribution sets against its declared collective (ACCL501-504,
    strict and unbudgeted: a config the certifier cannot lift fails the
    gate — inability must never read as certified). `sample=N` keeps a
    deterministic ~N-config slice (CI's deep tier)."""
    import time as _time

    t0 = _time.monotonic()
    ok = True
    rooted = (Operation.bcast, Operation.scatter, Operation.gather,
              Operation.reduce)
    tunings = {
        "default": TuningParams.default(DEFAULT_MAX_RENDEZVOUS_SIZE),
        # force the binary-tree / capped-fan-in branches
        "trees": TuningParams(
            gather_flat_tree_max_fanin=2,
            gather_flat_tree_max_count=64,
            bcast_flat_tree_max_ranks=2,
            reduce_flat_tree_max_ranks=2,
            reduce_flat_tree_max_count=64,
            allreduce_composition_max_count=1 << 30,
        ),
    }
    scens = (Operation.bcast, Operation.scatter, Operation.gather,
             Operation.reduce, Operation.allgather, Operation.allreduce,
             Operation.reduce_scatter, Operation.alltoall,
             Operation.barrier, Operation.send)
    configs = []
    for world in (2, 4, 8):
        for scen in scens:
            roots = range(world) if scen in rooted else (0,)
            for root in roots:
                for count in (16, 100_000):
                    for tname, tuning in tunings.items():
                        if scen == Operation.barrier and count != 16:
                            continue
                        configs.append((world, scen, root, count,
                                        tname, tuning, DataType.none))
        # the quantized-wire cells: the families with int8 ring variants
        # (codes relayed, accumulation only at combine points) plus the
        # pairwise exchange (packed codes+scales, one message per hop —
        # both the block-aligned encode-once form at 8192 and the
        # per-hop form at 16) — both the protocol interpretation and
        # the semantic certifier must hold through the encoded datapath
        for scen in (Operation.allreduce, Operation.reduce_scatter,
                     Operation.allgather, Operation.alltoall):
            for count in (16, 8192):
                configs.append((world, scen, 0, count, "default",
                                tunings["default"], DataType.int8))
        # alltoallv cells: the capacity-bounded exchange
        # (schedules.alltoallv_schedule) with uniform-trim and
        # heterogeneous per-peer capacity vectors, exact and quantized
        # wire — the certifier must prove the routed prefix AND the
        # dropped (zero) tail of every slot (the MoE overflow-drop
        # semantics as descriptors)
        for count, pattern in ((300, "uniform"), (1024, "hetero")):
            if pattern == "uniform":
                pc = (max(count // 2, 1),) * world
            else:
                pc = tuple(max(count // (i + 1), 1) for i in range(world))
            for wire in (DataType.none, DataType.int8):
                configs.append((world, Operation.alltoall, 0, count,
                                "default", tunings["default"], wire,
                                ("a2av", pc)))
        # synthesized-schedule cells (sequencer/synthesis.py): payloads
        # inside the committed library entries' winning windows,
        # selected via maxed synth crossover registers — the lowered
        # hop-DAG programs must interpret, model-check and certify
        # exactly like the hand-written zoo (strict under --semantic).
        # Cells whose (op, world, size) no entry serves fall through to
        # the hand-written plan and stay valid sweep rows.
        synth_tuning = TuningParams(
            synth_allreduce_max_count=1 << 22,
            synth_allgather_max_count=1 << 22,
            synth_reduce_scatter_max_count=1 << 22,
        )
        for scen, count, wire in (
                (Operation.allreduce, 1024, DataType.none),
                (Operation.allreduce, 1024, DataType.int8),
                (Operation.reduce_scatter, 1024, DataType.none),
                (Operation.allgather, 65536, DataType.none)):
            configs.append((world, scen, 0, count, "synth",
                            synth_tuning, wire))
        # stripe-overlapped allreduce cells (sequencer/plan.py's
        # OVERLAP_MIN_COUNT window + timing.best_overlap_stripes):
        # the register-selected striped segmentation must interpret,
        # model-check and certify exactly like the unstriped ring.
        # Config tuples grow a trailing ("olap", stripes) extra; the
        # depth is pinned per cell the way the hier sweep pins its
        # stripe depths.
        olap_tuning = TuningParams(overlap_min_count=1)
        for count, stripes in ((64, 2), (4096, 4)):
            configs.append((world, Operation.allreduce, 0, count,
                            "olap", olap_tuning, DataType.none,
                            ("olap", stripes)))
        # degraded live-subset allreduce cells (accl_tpu/resilience/,
        # docs/resilience.md): the source-masked ring selected through
        # live_ranks — the certifier must prove the answer sums EXACTLY
        # the declared survivor set (all-but-one and a half-world set;
        # deduplicated — at world 2 the two coincide)
        for count in (16, 8192):
            for lr in sorted({
                    tuple(r for r in range(world) if r != world - 1),
                    tuple(range(max(world // 2, 1)))}):
                configs.append((world, Operation.allreduce, 0, count,
                                "live", tunings["default"], DataType.none,
                                ("live", lr)))
    # hierarchical two-tier cells (sequencer/hierarchical.py): the
    # striped composition selected through the register window for
    # every (inner, outer) factoring, several stripe depths, and the
    # per-tier wire combinations — each must interpret, model-check and
    # certify exactly like the flat zoo. Config tuples grow a trailing
    # (topology, tier_wires, stripes) extra; None for the flat sweep.
    # MIN register: any positive payload >= 1 byte selects the
    # composition, so every sweep size below exercises it
    hier_tuning = TuningParams(hier_allreduce_min_count=1)
    for world, factorings in ((4, ((2, 2),)), (8, ((2, 4), (4, 2)))):
        for L, P in factorings:
            for count, stripes in ((64, 1), (8192, 2)):
                for tw in ((DataType.none, DataType.none),
                           (DataType.none, DataType.int8),
                           (DataType.float16, DataType.none)):
                    configs.append((world, Operation.allreduce, 0, count,
                                    "hier", hier_tuning, DataType.none,
                                    ("hier", (L, P), tw, stripes)))
    # tiered synthesized cells (sequencer/synthesis.py factored
    # families): the committed tiered hop-DAG selected through the
    # REAL in-window arbitration (the hier register + a declared
    # topology + the predicted-time tie-break against the striped
    # composition) — its lowered body must interpret, model-check and
    # certify exactly like the composition it displaces. Config tuples
    # grow a trailing ("synth_tier", topology) extra; the plain hier
    # rows above pin the composition itself via tiered_synth_ok=False.
    for world, topo, count in ((8, (2, 4), 8192), (8, (2, 4), 65536)):
        configs.append((world, Operation.allreduce, 0, count,
                        "synth_tier", hier_tuning, DataType.none,
                        ("synth_tier", topo)))
    if sample and sample < len(configs):
        # deterministic slice: every ceil(total/sample)-th config, so
        # the CI subset is stable across runs and spans all families
        stride = -(-len(configs) // sample)
        configs = configs[::stride]
    n = 0
    budget = Budget()
    for cfg in configs:
        world, scen, root, count, tname, tuning, wire = cfg[:7]
        extra = cfg[7] if len(cfg) > 7 else None
        hier = extra[1:] if extra is not None and extra[0] == "hier" \
            else None
        a2av = extra[1] if extra is not None and extra[0] == "a2av" \
            else None
        olap = extra[1] if extra is not None and extra[0] == "olap" \
            else None
        synth_tier = (extra[1] if extra is not None
                      and extra[0] == "synth_tier" else None)
        live = extra[1] if extra is not None and extra[0] == "live" \
            else None
        from accl_tpu.constants import CompressionFlags

        rsd = root if scen != Operation.send \
            else 0 | ((world - 1) << 16)
        comp_flags = (CompressionFlags.ETH_COMPRESSED
                      if wire != DataType.none
                      else CompressionFlags.NO_COMPRESSION)
        opts = CallOptions(
            scenario=scen, count=count, root_src_dst=rsd,
            function=int(ReduceFunction.SUM),
            data_type=DataType.float32,
            compress_dtype=wire, compression_flags=comp_flags,
            peer_counts=a2av or (), live_ranks=live or ())
        hier_kw: dict = {}
        if hier is not None or synth_tier is not None:
            from accl_tpu.sequencer.timing import LinkParams, TierLinks
        if hier is not None:
            topo, tier_wires, stripes = hier

            # a representative fast-inner/slow-outer calibration: only
            # the stripe count depends on it, and the sweep pins the
            # depth explicitly below. tiered_synth_ok=False pins the
            # COMPOSITION through the twin-measurement escape — the
            # in-window arbitration would otherwise resolve these
            # cells to the committed tiered entries, which have their
            # own synth_tier rows below
            hier_kw = dict(topology=topo, tier_wires=tier_wires,
                           tiered_synth_ok=False,
                           tier_links=TierLinks(
                               inner=LinkParams(2e-6, 2e9),
                               outer=LinkParams(30e-6, 0.25e9)))
        if synth_tier is not None:
            # a WAN-class outer link (the hier-gate's shaped regime):
            # per-message latency on the slow tier dominates, which is
            # exactly where the log-step tiered entries displace the
            # striped composition in the arbitration
            hier_kw = dict(topology=synth_tier,
                           tier_links=TierLinks(
                               inner=LinkParams(2e-6, 2e9),
                               outer=LinkParams(300e-6, 0.25e9)))
        olap_kw: dict = {}
        if olap is not None:
            from accl_tpu.sequencer.timing import (ComputeFit,
                                                   LinkParams)

            # a representative shaped-link + compute calibration: the
            # register must engage through the REAL selection path;
            # the sweep pins the stripe depth explicitly below
            olap_kw = dict(overlap_link=LinkParams(600e-6, 0.3e9),
                           overlap_compute=ComputeFit(2e-3, 0.3e9))
        plan = select_algorithm(
            scen, count, 4, world, comp_flags,
            max_eager_size=DEFAULT_MAX_EAGER_SIZE,
            eager_rx_buf_size=DEFAULT_EAGER_RX_BUF_SIZE,
            tuning=tuning, compress_dtype=wire,
            peer_counts=a2av or (), live_ranks=live or (),
            **hier_kw, **olap_kw)
        if live is not None:
            assert plan.algorithm.name == "EAGER_RING_RS_AG" \
                and plan.live_ranks == live, \
                f"live-subset config did not select the masked ring: {plan}"
        if olap is not None:
            import dataclasses as _dc

            assert plan.algorithm.name == "EAGER_RING_RS_AG" \
                and plan.stripes > 1, \
                f"overlap config did not stripe the ring: {plan}"
            seg = -(-count // olap)
            seg += (-seg) % world
            plan = _dc.replace(plan, stripes=olap, seg_count=seg,
                               num_segments=max(-(-count // seg), 1))
        if a2av is not None:
            assert plan.algorithm.name == "FLAT_ALLTOALLV", \
                f"alltoallv config did not select the v-schedule: {plan}"
        if hier is not None:
            import dataclasses as _dc

            assert plan.algorithm.name == "HIER_RS_AR_AG", \
                f"hier config did not select the composition: {plan}"
            plan = _dc.replace(plan, stripes=hier[2])
        if synth_tier is not None:
            assert plan.algorithm.name == "SYNTHESIZED" \
                and plan.synth_key, \
                f"synth_tier config did not arbitrate to a tiered " \
                f"entry: {plan}"
        # trace each schedule body ONCE (the dominant cost): the hops
        # feed the per-config interpretation AND, under --deep, the
        # exhaustive-interleaving checker
        hops = trace_schedule_hops(opts, plan, world)
        diags = check_hops(hops, world)
        if not diags:
            programs = rank_programs_from_hops(hops, world)
            diags = simulate(programs, blocking_sends=False)
            if deep and not diags:
                diags = SequenceLinter(
                    world, budget=budget).check_interleavings(programs)
                # ANY truncation fails the deep gate: a partial sweep
                # must never read as a clean one
                if any(d.code == "ACCL207" for d in diags):
                    ok = False
            if semantic and not diags:
                # strict: UnsupportedSchedule is a gate failure, never
                # a silent pass
                try:
                    diags = semantics_mod.check_batch_semantics(
                        [opts], [plan], world, strict=True)
                except semantics_mod.UnsupportedSchedule as e:
                    ok = False
                    print(f" FAIL {scen.name} world={world} "
                          f"count={count}: certifier cannot lift: {e}")
        n += 1
        if diags:
            ok = False
            print(f" FAIL {scen.name} world={world} "
                  f"root={root} count={count} "
                  f"tuning={tname} wire={wire.name} "
                  f"{plan.algorithm.name}: "
                  f"{[str(d) for d in diags]}")
    dt = _time.monotonic() - t0
    print(f"schedules: {n} (scenario, world, root, size, tuning, wire) "
          f"configurations interpreted"
          + (" + model-checked" if deep else "")
          + (" + semantically certified" if semantic else "") + " "
          + ("clean" if ok else "WITH DEFECTS")
          + f" in {dt:.1f}s")
    return ok


def run_interference() -> bool:
    """The cross-program pair sweep (the --interference gate):

    1. footprints over every shipped schedule family with DISJOINT
       buffer arenas pairwise-certify clean via summaries alone — the
       escalation counter must stay 0 (the O(N^2)-cheap fast path the
       multi-tenant admission control relies on);
    2. adversarial rows reject with their EXACT codes: a shared-region
       pair ACCL601, a pallas-ring slot pair ACCL603, a wildcard-steal
       rank-program pair ACCL602, an unliftable footprint ACCL604;
    3. the recorded MoE / decode / train-step programs (REAL recorders
       over a virtual mesh, no XLA compile) pairwise-certify clean or
       reject with a stable ACCL6xx — never ACCL604: every shipped
       program family must be liftable."""
    import time as _time

    from accl_tpu.analysis.interference import (
        InterferenceCertifier, footprint_from_rank_programs,
        footprint_from_steps)
    from accl_tpu.analysis.protocol import recv, send

    t0 = _time.monotonic()
    ok = True

    # -- 1. disjoint-arena family sweep: summaries alone, zero
    #       escalations ------------------------------------------------
    families = [
        ("allreduce", [dict(op="allreduce", count=4096)]),
        ("quantized", [dict(op="allreduce", count=8192,
                            compress="int8")]),
        ("rs_ag", [dict(op="reduce_scatter", count=1024),
                   dict(op="allgather", count=1024)]),
        ("alltoall", [dict(op="alltoall", count=512)]),
        ("alltoallv", [dict(op="alltoall", count=300)]),
        ("bcast_gather", [dict(op="bcast", count=256),
                          dict(op="gather", count=256)]),
        ("hier", [dict(op="allreduce", count=8192)]),
        ("decode_like", [dict(op="copy", count=64),
                         dict(op="allreduce", count=64),
                         dict(op="combine", count=64)]),
        ("train_like", [dict(op="copy", count=2048),
                        dict(op="allreduce", count=2048),
                        dict(op="combine", count=2048)]),
    ]

    def arena_steps(rows: list, base: int, world: int):
        steps = []
        nxt = [base]

        def alloc() -> int:
            nxt[0] += 0x100000
            return nxt[0]

        for row in rows:
            d = dict(row)
            d["addr_0"] = alloc()
            if d["op"] == "combine":
                d["addr_1"] = alloc()
            d["addr_2"] = alloc()
            if d["op"] == "alltoall" and d["count"] == 300:
                # alltoallv footprint rides the same descriptor shape;
                # peer_counts don't change the prefix access model
                pass
            steps.append(_step_from_dict(d))
        return steps

    n_pairs = 0
    for world in (2, 4, 8):
        certifier = InterferenceCertifier()
        fps = []
        for i, (name, rows) in enumerate(families):
            steps = arena_steps(rows, 0x10000000 * (i + 1), world)
            plans = tuple(_default_plan(o, world) for o in steps)
            fps.append(footprint_from_steps(
                steps, world, plans=plans, label=f"{name}@{world}"))
        bad = [fp for fp in fps if fp.unliftable is not None]
        if bad:
            ok = False
            for fp in bad:
                print(f" FAIL {fp.label}: unliftable footprint "
                      f"({fp.unliftable})")
        diags = certifier.certify(fps)
        n_pairs += certifier.pairs_checked
        if diags:
            ok = False
            for d in diags:
                print(f" FAIL disjoint sweep world={world}: {d}")
        if certifier.escalations:
            ok = False
            print(f" FAIL disjoint sweep world={world}: "
                  f"{certifier.escalations} escalations (summary-only "
                  "fast path violated)")

    # -- 2. adversarial rows: exact codes ------------------------------
    def expect_exact(title: str, fps, codes: set) -> None:
        nonlocal ok
        got = {d.code for d in InterferenceCertifier().certify(fps)}
        if got != codes:
            ok = False
            print(f" FAIL {title}: expected exactly {sorted(codes)}, "
                  f"got {sorted(got)}")

    world = 4
    a = arena_steps([dict(op="allreduce", count=256)], 0x10000000, world)
    b = arena_steps([dict(op="allreduce", count=256)], 0x20000000, world)
    shared = arena_steps([dict(op="allreduce", count=256)], 0x10000000,
                         world)
    mk = lambda s, label, **kw: footprint_from_steps(  # noqa: E731
        s, world, plans=tuple(_default_plan(o, world) for o in s),
        label=label, **kw)
    expect_exact("overlap pair", [mk(a, "A"), mk(shared, "B")],
                 {"ACCL601"})
    expect_exact("slot pair",
                 [mk(a, "A", use_pallas_ring=True),
                  mk(b, "B", use_pallas_ring=True)], {"ACCL603"})
    steal_a = footprint_from_rank_programs(
        [[recv(1, TAG_ANY, 4)], [send(0, 3, 4)]], 2, label="A")
    steal_b = footprint_from_rank_programs(
        [[recv(1, 9, 4)], [send(0, 9, 4)]], 2, label="B")
    expect_exact("steal pair", [steal_a, steal_b], {"ACCL602"})
    broken = footprint_from_steps([object()], world, label="broken")
    expect_exact("unliftable pair", [mk(a, "A"), broken], {"ACCL604"})

    # -- 3. recorded model-program pairs (real recorders, no compile) --
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from accl_tpu import ACCL
    from accl_tpu.models import moe as moe_mod
    from accl_tpu.models import transformer as trf

    # world 4: the tp decode step needs world | n_heads, and 4 is the
    # widest the tiny sweep config supports (the footprint layer itself
    # is world-agnostic — worlds 2-8 are covered by the sweep above)
    n_dev = min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("ccl",))
    accl = ACCL(mesh)

    def rec_footprint(rec, label: str):
        return footprint_from_steps(
            rec.calls, accl.world, persistent=rec._persistent,
            label=label)

    model_fps = []
    for tag in ("moe", "moe2"):
        disp, mid, out = (accl.create_buffer(1024, np.float32)
                          for _ in range(3))
        seq = accl.sequence()
        seq.alltoall(disp, mid, 128,
                     res_stream=moe_mod.MOE_EXPERT_STREAM)
        seq.alltoall(mid, out, 128)
        model_fps.append(rec_footprint(seq, tag))
    cfg = trf.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64)
    params = trf.init_params(cfg, jax.random.key(0))
    rec, _ = trf.record_decode_step(accl, cfg, params, batch=2,
                                    max_len=8)
    model_fps.append(rec_footprint(rec, "decode"))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab,
                          (accl.world, 1, 8)).astype(np.int32)
    rec, _ = trf.record_train_step(accl, cfg, tokens,
                                   np.roll(tokens, -1, axis=2))
    model_fps.append(rec_footprint(rec, "train"))

    certifier = InterferenceCertifier()
    for i in range(len(model_fps)):
        for j in range(i + 1, len(model_fps)):
            fa, fb = model_fps[i], model_fps[j]
            diags = certifier.check_pair(fa, fb)
            n_pairs += 1
            codes = sorted({d.code for d in diags})
            if "ACCL604" in codes:
                ok = False
                print(f" FAIL {fa.label} x {fb.label}: ACCL604 — a "
                      "shipped program family must be liftable")
            print(f"  {fa.label:8s} x {fb.label:8s} "
                  + ("clean" if not codes else str(codes)))

    # -- 4. the multi-tenant corpus rows: every kind=="concurrent"
    #       fixture replays through run_fixture_file, so the sweep and
    #       the corpus can never disagree about a tenant mix -----------
    n_corpus = 0
    for path in sorted(DEFAULT_CORPUS.glob("*.json")):
        try:
            if json.loads(path.read_text()).get("kind") != "concurrent":
                continue
            fok, line = run_fixture_file(path)
        except Exception as e:  # a crashing fixture is a failing one
            fok, line = False, (f"{path.name:40s} ERROR "
                                f"{type(e).__name__}: {e}")
        n_corpus += 1
        ok &= fok
        print(("  ok  " if fok else " FAIL ") + line)

    dt = _time.monotonic() - t0
    print(f"interference: {n_pairs} pairs certified across the family "
          f"sweep, adversarial rows and recorded model programs, "
          f"{n_corpus} concurrent corpus fixtures replayed "
          + ("clean" if ok else "WITH DEFECTS") + f" in {dt:.1f}s")
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--corpus", nargs="?", const=str(DEFAULT_CORPUS),
                    default=None, metavar="DIR",
                    help="replay the fixture corpus (default "
                         "tools/lint_corpus/)")
    ap.add_argument("--schedules", action="store_true",
                    help="interpret every shipping schedule and require "
                         "it clean")
    ap.add_argument("--deep", action="store_true",
                    help="force the exhaustive-interleaving tier on "
                         "fixtures and --schedules (ACCL205-207)")
    ap.add_argument("--semantic", action="store_true",
                    help="semantically certify every --schedules config "
                         "against its declared collective "
                         "(ACCL501-504, strict)")
    ap.add_argument("--sample", type=int, default=0, metavar="N",
                    help="deterministically subsample --schedules to "
                         "~N configurations")
    ap.add_argument("--interference", action="store_true",
                    help="cross-program pair sweep: pairwise-certify "
                         "concurrent footprints over the shipped "
                         "schedule families, adversarial rows and the "
                         "recorded model programs (ACCL601-604)")
    ap.add_argument("files", nargs="*", help="individual fixture files")
    args = ap.parse_args(argv)
    if not (args.corpus or args.schedules or args.interference
            or args.files):
        ap.error("nothing to do: pass --corpus, --schedules, "
                 "--interference, or files")
    ok = True
    if args.corpus:
        ok &= run_corpus(pathlib.Path(args.corpus), deep=args.deep)
    if args.schedules:
        ok &= run_schedules(deep=args.deep, sample=args.sample,
                            semantic=args.semantic)
    if args.interference:
        ok &= run_interference()
    for f in args.files:
        fok, line = run_fixture_file(pathlib.Path(f), deep=args.deep)
        ok &= fok
        print(("  ok  " if fok else " FAIL ") + line)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
